"""Pure shard-routing kernel: vectorized worker assignment for columnar batches.

Extracted from the sharded scheduler so the routing math is directly
testable and shared by BOTH exchange paths — the in-process lockstep
scheduler (engine/sharded.py) and the multiprocess TCP mesh
(engine/distributed.py) call the same :func:`columnar_shards`, so a row can
never land on a different worker depending on which transport carried it.

The contract mirrors the reference's exchange pacts (timely exchange
channels partition records by a hash of the key, never a per-row
interpreted loop): given a partition rule from
:func:`pathway_tpu.engine.sharded.partition_rule` and a
:class:`~pathway_tpu.engine.batch.Columns` payload, produce an int64 worker
id per row — or ``None`` whenever the vectorized assignment cannot be
digest-identical to the per-row partitioners, in which case the caller
falls back to the row path. The kernel never raises on data it cannot
handle; ``None`` IS the error channel.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Sequence

import numpy as np

from pathway_tpu.engine.value import (
    ERROR,
    Pointer,
    _digest16,
    hash_values,
    hash_values_batch,
)
from pathway_tpu.internals import metrics as _metrics
from pathway_tpu.native import kernels as _native

if TYPE_CHECKING:  # pragma: no cover
    from pathway_tpu.engine.batch import Columns

__all__ = [
    "EXCHANGE_STATS",
    "batch_shards",
    "columnar_shards",
    "entry_shards",
    "mod_u128_bytes",
    "reshard_moves",
    "shards_of_values",
]

#: exchange-path probe counters, shared by the in-process scheduler and
#: the TCP mesh (engine/distributed.py re-exports this same dict object).
#: ``elided`` counts deliveries that skipped routing entirely because the
#: optimizer proved the exchange redundant (pathway_tpu.optimize.elide).
#: Writes mirror into the ``pathway_exchange_events_total{kind=...}``
#: registry counters (internals/metrics.py) while this dict stays the
#: authoritative alias all three import paths share.
#:
#: Every per-(consumer, port) delivery decision increments exactly ONE of
#: ``elided`` / ``host_deliveries`` / ``collective_deliveries`` AND
#: ``repartitions`` — so ``elided + host + collective == repartitions``
#: holds at all times (cross-checked by tests/test_collective_exchange.py).
#: The mirrored series carry a ``path`` label (elided / host / device /
#: total) distinguishing the delivery plane per edge.
EXCHANGE_STATS = _metrics.MirroredCounterDict(
    "pathway_exchange_events_total",
    "kind",
    {
        "columnar_frames_sent": 0,
        "columnar_frames_received": 0,
        "row_batches_sent": 0,
        "elided": 0,
        "host_deliveries": 0,
        "collective_deliveries": 0,
        "repartitions": 0,
    },
    help="exchange-path events by kind (mirrors EXCHANGE_STATS)",
    extra_labels={
        "columnar_frames_sent": {"path": "host"},
        "columnar_frames_received": {"path": "host"},
        "row_batches_sent": {"path": "host"},
        "elided": {"path": "elided"},
        "host_deliveries": {"path": "host"},
        "collective_deliveries": {"path": "device"},
        "repartitions": {"path": "total"},
    },
)


def _shard_of(value: Any, n: int) -> int:
    """Per-row worker assignment — THE definition of which worker owns a
    value; everything vectorized below must agree with it bit for bit."""
    if isinstance(value, Pointer):
        return int(value) % n
    try:
        return int(hash_values((value,), salt=b"shard")) % n
    except TypeError:
        return int(hash_values((repr(value),), salt=b"shard")) % n


def _shard_digest_fallback(value: Any) -> bytes:
    """Digest for one value the native serializer bailed on — the tail of
    :func:`_shard_of` (TypeError -> repr) as a bytes-returning closure."""
    try:
        return _digest16((value,), b"shard")
    except TypeError:
        return _digest16((repr(value),), b"shard")


def mod_u128_bytes(kb: np.ndarray, n: int) -> np.ndarray:
    """Vectorized ``int.from_bytes(row, "little") % n`` over an ``(m, 16)``
    uint8 matrix of little-endian 128-bit integers (key digests).

    The halves fold via ``(hi * 2**64 + lo) % n ==
    ((hi % n) * (2**64 % n) + lo % n) % n``; every intermediate stays below
    ``n**2``, so the arithmetic is uint64-exact for any realistic worker
    count (n < 2**32)."""
    kb = np.ascontiguousarray(kb)
    lo = kb[:, :8].copy().view(np.uint64).ravel()
    hi = kb[:, 8:].copy().view(np.uint64).ravel()
    nn = np.uint64(n)
    base = np.uint64((1 << 64) % n)
    return (((hi % nn) * base + lo % nn) % nn).astype(np.int64)


def shards_of_values(values: Sequence[Any], n: int) -> np.ndarray:
    """Batched ``_shard_of``: when the native kernels are loaded, ONE
    ``shard_values`` call serializes, digests, and mods every value
    (Pointers short-circuit to ``int(v) % n`` on their key bytes);
    otherwise one :func:`hash_values_batch` call builds the digest matrix
    for every non-Pointer value and one vectorized mod folds it to worker
    ids. Callers pass DISTINCT representatives (factorize output), so any
    remaining per-value work runs per distinct key inside a single call —
    not per row on the exchange hot path."""
    if _native is not None and hasattr(_native, "shard_values"):
        vlist = values if isinstance(values, list) else list(values)
        got = _native.shard_values(
            vlist, b"shard", n, Pointer, ERROR, _shard_digest_fallback
        )
        if got is not None:
            return got
    shards = np.empty(len(values), np.int64)
    rows: list[tuple] = []
    where: list[int] = []
    for i, v in enumerate(values):
        if isinstance(v, Pointer):
            shards[i] = int(v) % n
        else:
            rows.append((v,))
            where.append(i)
    if rows:
        kb = hash_values_batch(rows, salt=b"shard", on_type_error="repr")
        shards[np.asarray(where, np.int64)] = mod_u128_bytes(kb, n)
    return shards


def reshard_moves(keys: Sequence[Any], n_old: int, n_new: int) -> int:
    """How many of ``keys`` change owners when the worker count goes
    ``n_old`` → ``n_new`` — the state-transfer volume of a snapshot
    re-shard (``engine/persistence.reshard_process_snapshots`` reports
    it per rescale).  Both assignments run through
    :func:`shards_of_values`, i.e. the exact digests live routing uses,
    so the count is exact rather than the ``1 - n_old/n_new`` estimate
    a consistent-hash analysis would give."""
    if not len(keys) or n_old == n_new:
        return 0
    vlist = keys if isinstance(keys, list) else list(keys)
    old = shards_of_values(vlist, n_old)
    new = shards_of_values(vlist, n_new)
    return int(np.count_nonzero(old != new))


def entry_shards(rule: tuple, entries: "Sequence[tuple]", n: int) -> np.ndarray | None:
    """Vectorized worker assignment for ROW entries — the row-path twin of
    :func:`columnar_shards`. One :func:`shards_of_values` call per batch
    replaces the per-row partitioner closure; the value extraction per
    rule mirrors sharded.partitioner exactly (``by_cols`` hashes the
    column TUPLE, ``by_col`` the bare value, ``by_key`` the row key).
    ``None`` for rules without a shard table (``pin``)."""
    kind = rule[0]
    if kind == "key":
        return shards_of_values([e[0] for e in entries], n)
    if kind == "cols":
        cols = rule[1]
        return shards_of_values(
            [tuple(e[1][c] for c in cols) for e in entries], n
        )
    if kind == "col":
        c = rule[1]
        if c is None:
            return shards_of_values([None] * len(entries), n)
        return shards_of_values([e[1][c] for e in entries], n)
    return None


def _object_codes(col: np.ndarray) -> np.ndarray:
    """Dense int64 codes for a non-sortable (object-dtype) column, keyed
    by the value's hash_values DIGEST — the exact identity the per-row
    partitioners use. Dict equality would be coarser (a tz-aware datetime
    equals its rebased twin but digests differently), which could route
    one logical key to different workers depending on which class member
    a batch sees first.

    One ``hash_values_batch`` call computes every digest; the codes come
    from a single ``np.unique`` over the digest matrix. (Code order
    differs from first-seen order, which is fine: ``factorize_multi``
    consumes only the identity classes, never the code values.)

    With the native kernels loaded the column array goes straight into
    ``hash_tuples_batch`` in bare mode — no ``[(v,) for v in tolist()]``
    materialization; the digests are identical by construction."""
    if _native is not None and hasattr(_native, "hash_tuples_batch"):
        kb = _native.hash_tuples_batch(
            np.ascontiguousarray(col), b"", True, Pointer, ERROR,
            _bare_digest_fallback,
        )
    else:
        kb = hash_values_batch(
            [(v,) for v in col.tolist()], on_type_error="repr"
        )
    _uniq, inverse = np.unique(kb, axis=0, return_inverse=True)
    return inverse.ravel().astype(np.int64, copy=False)


def _bare_digest_fallback(value: Any) -> bytes:
    """Unsalted single-value digest with the repr-on-TypeError rule —
    the per-item fallback ``_object_codes`` hands the native kernel."""
    try:
        return _digest16((value,), b"")
    except TypeError:
        return _digest16((repr(value),), b"")


def columnar_shards(
    rule: tuple, columns: "Columns", n: int
) -> np.ndarray | None:
    """Vectorized worker assignment for a columnar batch, or ``None`` when
    the routing rule needs the row path.

    Digest-identical to the per-row partitioners (engine/sharded.py):
    row-key routing is the full 128-bit pointer mod n; column routing
    hashes per DISTINCT value (``factorize_multi``) and maps back through
    the inverse index. Fallback rules (→ ``None``, never an exception):

    - ``("pin",)`` rules — the caller pushes the whole batch to worker 0
      without consulting a shard table;
    - column dtypes outside bool/int/float/unicode/object;
    - key-bytes derivation failure for ``("key",)`` batches.

    NaN-containing float columns stay vectorized: they factorize over
    their raw bit patterns, so distinct-bit NaNs keep the distinct
    digests the per-row partitioners would compute.
    """
    kind = rule[0]
    if kind in ("cols", "col"):
        if kind == "cols":
            idxs = list(rule[1])
            if len(idxs) == 0:
                return np.full(columns.n, _shard_of((), n), np.int64)
            bare = False  # by_cols hashes the value TUPLE
        else:
            c = rule[1]
            if c is None:
                return np.full(columns.n, _shard_of(None, n), np.int64)
            idxs = [c]
            bare = True  # by_col hashes the bare value
        from pathway_tpu.engine.device import factorize_multi

        arrays = []
        for c in idxs:
            col = columns.cols[c]
            if col.dtype.kind in "bifU":
                if col.dtype.kind == "f" and np.isnan(col).any():
                    # bit-pattern coding keeps distinct-bit NaNs apart —
                    # the identity the per-row digests use, which value
                    # factorization (NaN != NaN, payloads collapse)
                    # cannot express. Splitting FINER than value equality
                    # (+0.0 / -0.0 land in two classes) is safe: each
                    # class representative digests to the same shard.
                    arrays.append(
                        np.ascontiguousarray(col).view(
                            np.dtype(f"u{col.dtype.itemsize}")
                        )
                    )
                    continue
                arrays.append(col)
            elif col.dtype == object:
                arrays.append(_object_codes(col))
            else:
                return None
        first, inverse = factorize_multi(arrays)
        reps = zip(*(columns.cols[c][first].tolist() for c in idxs))
        if bare:
            table = shards_of_values([t[0] for t in reps], n)
        else:
            table = shards_of_values(list(reps), n)
        return table[inverse]
    if kind != "key":
        return None  # "pin" never reaches a shard table (fn is None earlier)
    try:
        kb = columns.kbytes()
    except Exception:  # lazy key thunk failed: the row path derives keys
        return None
    return mod_u128_bytes(kb, n)


def batch_shards(rule: tuple, batch: "Any", n: int) -> np.ndarray | None:
    """Worker id per row of a whole :class:`DeltaBatch` under ``rule`` —
    columnar kernel when the payload allows it, entry fallback otherwise;
    ``None`` for pin rules.  Debug/verification helper (the
    ``PATHWAY_TPU_VERIFY_ELISION=1`` cross-check and the elision tests),
    not an exchange hot path."""
    if batch._entries is None and batch.columns is not None:
        got = columnar_shards(rule, batch.columns, n)
        if got is not None:
            return got
    return entry_shards(rule, batch.entries, n)
