"""MeshSupervisor: launch, monitor, and restart the worker processes of a
distributed run.

``pathway spawn`` delegates here when ``PATHWAY_TPU_RECOVER`` is enabled
(cli.py); plain spawns keep the original launch-and-wait path.  The
supervisor is the control plane of the fault-tolerance layer:

- it launches the N worker processes with the same topology env wiring
  as ``cli.spawn`` (PATHWAY_THREADS/PROCESSES/PROCESS_ID/FIRST_PORT/
  RUN_ID, one shared PATHWAY_EXCHANGE_SECRET), remembering each child's
  exact environment for restarts;
- it watches for worker deaths.  A NON-LEADER worker that dies while the
  leader is still running is relaunched with its saved environment — the
  restarted process re-runs the whole program, reconnects the mesh,
  re-runs the topology handshake, and rejoins from its latest operator
  snapshot (internals/runner.py drives that protocol).  Restarts are
  bounded by ``PATHWAY_TPU_MAX_RESTARTS`` (default 3, per run);
- it services kill requests: the leader detects a HUNG (not dead) peer
  via the heartbeat suspicion timeout and writes ``kill-<id>`` into
  ``PATHWAY_TPU_SUPERVISOR_DIR``; the supervisor SIGKILLs that worker so
  the ordinary death→restart path takes over;
- leader death, restart-budget exhaustion, or a non-zero clean exit
  tears the whole mesh down and propagates the exit code with the same
  ``rc if rc > 0 else 128 - rc`` convention as ``cli.spawn``.
"""

from __future__ import annotations

import os
import secrets
import signal
import subprocess
import sys
import tempfile
import time as _time
import uuid
from typing import Sequence


class MeshSupervisor:
    def __init__(
        self,
        program: str,
        arguments: Sequence[str],
        *,
        threads: int = 1,
        processes: int = 1,
        first_port: int = 10000,
        env: dict | None = None,
        max_restarts: int | None = None,
        poll_interval: float = 0.05,
    ) -> None:
        self.program = program
        self.arguments = list(arguments)
        self.threads = threads
        self.processes = processes
        self.first_port = first_port
        if max_restarts is None:
            try:
                max_restarts = int(
                    os.environ.get("PATHWAY_TPU_MAX_RESTARTS", "3")
                )
            except ValueError:
                max_restarts = 3
        self.max_restarts = max(0, max_restarts)
        self.poll_interval = poll_interval
        self.restarts = 0

        env_base = dict(os.environ if env is None else env)
        self.recovery = env_base.get(
            "PATHWAY_TPU_RECOVER", ""
        ).lower() in ("1", "true", "yes")
        env_base.setdefault("PATHWAY_EXCHANGE_SECRET", secrets.token_hex(32))
        env_base.setdefault("PATHWAY_RUN_ID", str(uuid.uuid4()))
        self._kill_dir = tempfile.mkdtemp(prefix="pathway-supervisor-")
        env_base["PATHWAY_TPU_SUPERVISOR_DIR"] = self._kill_dir
        self._envs: list[dict] = []
        for process_id in range(processes):
            proc_env = env_base.copy()
            proc_env["PATHWAY_THREADS"] = str(threads)
            proc_env["PATHWAY_PROCESSES"] = str(processes)
            proc_env["PATHWAY_FIRST_PORT"] = str(first_port)
            proc_env["PATHWAY_PROCESS_ID"] = str(process_id)
            self._envs.append(proc_env)
        self._handles: list[subprocess.Popen | None] = [None] * processes
        #: final exit code of each slot once it will not run again
        self._final_rc: list[int | None] = [None] * processes
        #: restarts per slot — stamped into the child env so a re-parsed
        #: fault plan knows its kill fault already fired (engine/faults.py)
        self._slot_restarts = [0] * processes

    # -- process control -----------------------------------------------------

    def _launch(self, process_id: int) -> None:
        proc_env = dict(
            self._envs[process_id],
            PATHWAY_TPU_RESTART_COUNT=str(self._slot_restarts[process_id]),
        )
        self._handles[process_id] = subprocess.Popen(
            [self.program, *self.arguments], env=proc_env
        )

    def _terminate_all(self) -> None:
        for handle in self._handles:
            if handle is not None and handle.poll() is None:
                handle.terminate()
        deadline = _time.monotonic() + 5.0
        for handle in self._handles:
            if handle is None:
                continue
            while handle.poll() is None:
                if _time.monotonic() > deadline:
                    handle.kill()
                    break
                _time.sleep(0.02)

    def _service_kill_requests(self) -> None:
        try:
            names = os.listdir(self._kill_dir)
        except OSError:
            return
        for name in names:
            if not name.startswith("kill-"):
                continue
            try:
                target = int(name.split("-", 1)[1])
            except ValueError:
                continue
            try:
                os.unlink(os.path.join(self._kill_dir, name))
            except OSError:
                pass
            handle = (
                self._handles[target]
                if 0 <= target < self.processes
                else None
            )
            if handle is not None and handle.poll() is None:
                print(
                    f"pathway supervisor: killing hung worker {target} "
                    f"(pid {handle.pid}) on leader request",
                    file=sys.stderr,
                )
                handle.send_signal(signal.SIGKILL)

    # -- the supervision loop ------------------------------------------------

    def run(self) -> int:
        """Launch all workers and supervise until the mesh finishes or
        dies; returns the aggregated exit code (``cli.spawn`` convention)."""
        recovery = self.recovery
        print(
            f"Preparing {self.processes} process(es) "
            f"({self.processes * self.threads} total workers) "
            f"under supervision (recovery "
            f"{'on' if recovery else 'off'})",
            file=sys.stderr,
        )
        try:
            for process_id in range(self.processes):
                self._launch(process_id)
            while True:
                self._service_kill_requests()
                leader = self._handles[0]
                leader_rc = (
                    self._final_rc[0]
                    if self._final_rc[0] is not None
                    else (None if leader is None else leader.poll())
                )
                for process_id in range(self.processes):
                    if self._final_rc[process_id] is not None:
                        continue
                    handle = self._handles[process_id]
                    rc = None if handle is None else handle.poll()
                    if rc is None:
                        continue
                    if process_id == 0 or rc == 0 or not recovery:
                        self._final_rc[process_id] = rc
                        continue
                    if leader_rc is not None:
                        # the leader already finished: a late follower
                        # death is a teardown artifact, not a failure to
                        # recover from
                        self._final_rc[process_id] = rc
                        continue
                    if self.restarts >= self.max_restarts:
                        print(
                            f"pathway supervisor: worker {process_id} "
                            f"died (rc {rc}) with the restart budget "
                            f"exhausted ({self.max_restarts}); tearing "
                            "the mesh down",
                            file=sys.stderr,
                        )
                        self._final_rc[process_id] = rc
                        self._terminate_all()
                        break
                    self.restarts += 1
                    self._slot_restarts[process_id] += 1
                    print(
                        f"pathway supervisor: worker {process_id} died "
                        f"(rc {rc}); restarting "
                        f"({self.restarts}/{self.max_restarts})",
                        file=sys.stderr,
                    )
                    self._launch(process_id)
                if all(rc is not None for rc in self._final_rc):
                    break
                if self._final_rc[0] is not None:
                    # leader is done: give followers a moment to finish,
                    # then stop waiting on them
                    deadline = _time.monotonic() + 10.0
                    while _time.monotonic() < deadline and any(
                        h is not None and h.poll() is None
                        for h in self._handles
                    ):
                        _time.sleep(self.poll_interval)
                    self._terminate_all()
                    for pid_, handle in enumerate(self._handles):
                        if self._final_rc[pid_] is None:
                            self._final_rc[pid_] = (
                                handle.returncode
                                if handle is not None
                                and handle.returncode is not None
                                else 1
                            )
                    break
                _time.sleep(self.poll_interval)
        finally:
            self._terminate_all()
        for rc in self._final_rc:
            if rc is None:
                return 1
            if rc != 0:
                return rc if rc > 0 else 128 - rc
        return 0
