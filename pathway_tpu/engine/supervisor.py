"""MeshSupervisor: launch, monitor, restart, and rescale the worker
processes of a distributed run.

``pathway spawn`` delegates here when ``PATHWAY_TPU_RECOVER`` is enabled
(cli.py); plain spawns keep the original launch-and-wait path.  The
supervisor is the control plane of the fault-tolerance layer:

- it launches the N worker processes with the same topology env wiring
  as ``cli.spawn`` (PATHWAY_THREADS/PROCESSES/PROCESS_ID/FIRST_PORT/
  RUN_ID, one shared PATHWAY_EXCHANGE_SECRET), remembering the base
  environment so restarts — and rescales to a different N — rebuild each
  child's exact env;
- it watches for worker deaths.  A worker that dies by SIGNAL while
  recovery is on is relaunched with its saved environment — including
  the LEADER (process 0): the survivors elect an interim leader, the
  restarted process 0 re-runs the startup handshake above the
  survivors' fencing epoch, and the mesh rolls back to the last common
  commit (internals/runner.py drives that protocol).  A follower that
  dies with any non-zero code is likewise restarted.  Restarts are
  bounded by ``PATHWAY_TPU_MAX_RESTARTS`` (default 3, per run);
- it services kill requests: the leader (or, after leader loss, the
  interim leader) detects a HUNG peer via the heartbeat suspicion
  timeout and writes ``kill-<id>`` into ``PATHWAY_TPU_SUPERVISOR_DIR``;
  the supervisor SIGKILLs that worker so the ordinary death→restart
  path takes over;
- it services rescale requests (:meth:`rescale` or the
  ``pathway_tpu.cli rescale`` command writing a ``rescale`` file into
  the supervisor dir): it asks the mesh to quiesce at a commit
  boundary (workers snapshot and exit ``EXIT_QUIESCED``), re-shards the
  operator snapshots for the new process count with a one-shot helper
  child (``PATHWAY_TPU_RESHARD``), and relaunches the mesh at the new
  size — sinks resume exactly-once through their durable offset
  sidecars because the run id is preserved.  A fault mid-quiesce
  aborts the rescale and falls back to ordinary recovery;
- unrecoverable deaths tear the whole mesh down and propagate the exit
  code with the same ``rc if rc > 0 else 128 - rc`` convention as
  ``cli.spawn``.  A leader lost to a signal WITHOUT a restart (recovery
  off, or budget exhausted) is reported as :data:`EXIT_LEADER_LOST`
  after a grace window in which every surviving worker dumps its flight
  ring (the dumps land in ``PATHWAY_TPU_FLIGHT_DIR`` or the workers'
  cwd as ``pathway_flight_p<id>_pid<pid>.json``).
"""

from __future__ import annotations

import json as _json
import os
import secrets
import signal
import subprocess
import sys
import tempfile
import time as _time
import uuid
from typing import Sequence

#: supervisor exit code when the leader died by signal and could not be
#: restarted (recovery off or restart budget exhausted) — distinct so
#: harnesses can triage "leader lost" from ordinary worker failures
EXIT_LEADER_LOST = 75
#: worker exit code meaning "I snapshotted at the agreed commit boundary
#: and stopped for a pending rescale" — not a failure
EXIT_QUIESCED = 76

#: name of the rescale-request file inside the supervisor dir
RESCALE_REQUEST = "rescale"
#: name of the quiesce-marker file the leader polls at commit boundaries
QUIESCE_MARKER = "quiesce"


class MeshSupervisor:
    def __init__(
        self,
        program: str,
        arguments: Sequence[str],
        *,
        threads: int = 1,
        processes: int = 1,
        first_port: int = 10000,
        env: dict | None = None,
        max_restarts: int | None = None,
        poll_interval: float = 0.05,
    ) -> None:
        self.program = program
        self.arguments = list(arguments)
        self.threads = threads
        self.processes = processes
        self.first_port = first_port
        if max_restarts is None:
            # resolve from the same env the workers will see — callers
            # (cli.spawn, tests) pass the knob in `env`, not necessarily
            # in this process's own environment
            knobs = os.environ if env is None else env
            try:
                max_restarts = int(
                    knobs.get("PATHWAY_TPU_MAX_RESTARTS", "3")
                )
            except ValueError:
                max_restarts = 3
        self.max_restarts = max(0, max_restarts)
        self.poll_interval = poll_interval
        self.restarts = 0
        self.rescales = 0
        self.last_rescale_report: dict | None = None
        #: request-to-relaunch wall time of the last completed rescale
        self.last_rescale_wall_s: float | None = None

        env_base = dict(os.environ if env is None else env)
        self.recovery = env_base.get(
            "PATHWAY_TPU_RECOVER", ""
        ).lower() in ("1", "true", "yes")
        env_base.setdefault("PATHWAY_EXCHANGE_SECRET", secrets.token_hex(32))
        env_base.setdefault("PATHWAY_RUN_ID", str(uuid.uuid4()))
        # honor a caller-chosen supervisor dir (so `pathway_tpu.cli
        # rescale` can find it from another terminal); otherwise make a
        # private one
        preset = env_base.get("PATHWAY_TPU_SUPERVISOR_DIR")
        if preset:
            os.makedirs(preset, exist_ok=True)
            self._kill_dir = preset
        else:
            self._kill_dir = tempfile.mkdtemp(prefix="pathway-supervisor-")
            env_base["PATHWAY_TPU_SUPERVISOR_DIR"] = self._kill_dir
        self._env_base = env_base
        self._envs = self._build_envs()
        self._handles: list[subprocess.Popen | None] = [None] * processes
        #: final exit code of each slot once it will not run again
        self._final_rc: list[int | None] = [None] * processes
        #: restarts per slot — stamped into the child env so a re-parsed
        #: fault plan knows its kill fault already fired (engine/faults.py)
        self._slot_restarts = [0] * processes
        #: rescale state: requested target size, quiesced slots, timing
        self._rescale_target: int | None = None
        self._rescale_t0 = 0.0
        self._rescale_deadline = 0.0
        self._quiesced: set[int] = set()
        self._leader_lost = False

    def _build_envs(self) -> list[dict]:
        envs: list[dict] = []
        for process_id in range(self.processes):
            proc_env = self._env_base.copy()
            proc_env["PATHWAY_THREADS"] = str(self.threads)
            proc_env["PATHWAY_PROCESSES"] = str(self.processes)
            proc_env["PATHWAY_FIRST_PORT"] = str(self.first_port)
            proc_env["PATHWAY_PROCESS_ID"] = str(process_id)
            envs.append(proc_env)
        return envs

    # -- process control -----------------------------------------------------

    def _launch(self, process_id: int) -> None:
        proc_env = dict(
            self._envs[process_id],
            PATHWAY_TPU_RESTART_COUNT=str(self._slot_restarts[process_id]),
        )
        self._handles[process_id] = subprocess.Popen(
            [self.program, *self.arguments], env=proc_env
        )

    def _terminate_all(self) -> None:
        for handle in self._handles:
            if handle is not None and handle.poll() is None:
                handle.terminate()
        deadline = _time.monotonic() + 5.0
        for handle in self._handles:
            if handle is None:
                continue
            while handle.poll() is None:
                if _time.monotonic() > deadline:
                    handle.kill()
                    break
                _time.sleep(0.02)

    def _drain(self, grace_s: float) -> None:
        """Wait up to ``grace_s`` for still-live workers to exit on
        their own (e.g. to finish dumping flight rings)."""
        deadline = _time.monotonic() + grace_s
        while _time.monotonic() < deadline and any(
            h is not None and h.poll() is None for h in self._handles
        ):
            _time.sleep(self.poll_interval)

    def _service_kill_requests(self) -> None:
        try:
            names = os.listdir(self._kill_dir)
        except OSError:
            return
        for name in names:
            if not name.startswith("kill-"):
                continue
            try:
                target = int(name.split("-", 1)[1])
            except ValueError:
                continue
            try:
                os.unlink(os.path.join(self._kill_dir, name))
            except OSError:
                pass
            handle = (
                self._handles[target]
                if 0 <= target < self.processes
                else None
            )
            if handle is not None and handle.poll() is None:
                print(
                    f"pathway supervisor: killing hung worker {target} "
                    f"(pid {handle.pid}) on leader request",
                    file=sys.stderr,
                )
                handle.send_signal(signal.SIGKILL)

    # -- rescaling -----------------------------------------------------------

    def rescale(self, target: int) -> None:
        """Request a live N→M rescale.  The request is serviced by the
        supervision loop: the mesh quiesces at its next commit boundary,
        snapshots are re-sharded for ``target`` processes, and the mesh
        relaunches at the new size with bit-identical sink output."""
        path = os.path.join(self._kill_dir, RESCALE_REQUEST)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(str(int(target)))
        os.replace(tmp, path)

    def _service_rescale_request(self) -> None:
        if self._rescale_target is not None:
            return
        path = os.path.join(self._kill_dir, RESCALE_REQUEST)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                raw = fh.read().strip()
            os.unlink(path)
        except OSError:
            return
        try:
            target = int(raw)
        except ValueError:
            print(
                f"pathway supervisor: ignoring malformed rescale "
                f"request {raw!r}",
                file=sys.stderr,
            )
            return
        if target < 1 or target == self.processes:
            print(
                f"pathway supervisor: ignoring rescale request to "
                f"{target} (currently {self.processes})",
                file=sys.stderr,
            )
            return
        try:
            timeout = float(
                os.environ.get("PATHWAY_TPU_RESCALE_TIMEOUT", "120")
            )
        except ValueError:
            timeout = 120.0
        self._rescale_target = target
        self._rescale_t0 = _time.monotonic()
        self._rescale_deadline = self._rescale_t0 + timeout
        self._quiesced = set()
        marker = os.path.join(self._kill_dir, QUIESCE_MARKER)
        with open(marker, "w", encoding="utf-8") as fh:
            fh.write(str(target))
        print(
            f"pathway supervisor: rescale {self.processes} -> {target} "
            "requested; quiescing at the next commit boundary",
            file=sys.stderr,
        )

    def _cancel_rescale(self, reason: str) -> None:
        """Abort a pending rescale (fault mid-quiesce, or timeout) and
        relaunch any already-quiesced workers so ordinary recovery can
        take over.  Quiesced workers exited cleanly at a commit
        boundary, so their relaunch is not charged to the restart
        budget."""
        print(
            f"pathway supervisor: rescale to {self._rescale_target} "
            f"aborted: {reason}",
            file=sys.stderr,
        )
        try:
            os.unlink(os.path.join(self._kill_dir, QUIESCE_MARKER))
        except OSError:
            pass
        self._rescale_target = None
        for process_id in sorted(self._quiesced):
            if (
                self._final_rc[process_id] is None
                and self._handles[process_id] is None
            ):
                self._launch(process_id)
        self._quiesced = set()

    def _finish_rescale(self) -> int | None:
        """All workers quiesced: re-shard the snapshots with a one-shot
        helper child, then relaunch the mesh at the new size.  Returns
        ``None`` on success, or a fatal exit code if re-sharding
        failed."""
        target = self._rescale_target
        assert target is not None
        old = self.processes
        try:
            os.unlink(os.path.join(self._kill_dir, QUIESCE_MARKER))
        except OSError:
            pass
        helper_env = self._env_base.copy()
        helper_env["PATHWAY_THREADS"] = str(self.threads)
        helper_env["PATHWAY_PROCESSES"] = str(target)
        helper_env["PATHWAY_PROCESS_ID"] = "0"
        helper_env["PATHWAY_FIRST_PORT"] = str(self.first_port)
        helper_env["PATHWAY_TPU_RESHARD"] = str(old)
        try:
            helper = subprocess.run(
                [self.program, *self.arguments],
                env=helper_env,
                capture_output=True,
                text=True,
                timeout=600,
            )
        except subprocess.TimeoutExpired:
            print(
                "pathway supervisor: snapshot re-shard helper timed "
                "out; aborting",
                file=sys.stderr,
            )
            return 1
        if helper.returncode != 0:
            print(
                f"pathway supervisor: snapshot re-shard helper failed "
                f"(rc {helper.returncode}):\n{helper.stderr}",
                file=sys.stderr,
            )
            return helper.returncode if helper.returncode > 0 else 1
        report: dict = {}
        for line in helper.stdout.splitlines():
            if line.startswith("PATHWAY_RESHARD_JSON "):
                try:
                    report = _json.loads(
                        line[len("PATHWAY_RESHARD_JSON "):]
                    )
                except ValueError:
                    pass
        self.last_rescale_report = report
        wall = _time.monotonic() - self._rescale_t0
        self.last_rescale_wall_s = wall
        self.rescales += 1
        # the relaunched leader surfaces these as pathway_mesh_rescales_
        # total / pathway_mesh_rescale_seconds on its /metrics
        self._env_base["PATHWAY_TPU_RESCALED"] = str(self.rescales)
        self._env_base["PATHWAY_TPU_RESCALE_WALL_S"] = f"{wall:.6f}"
        old_slot_restarts = self._slot_restarts
        self.processes = target
        self._envs = self._build_envs()
        self._handles = [None] * target
        self._final_rc = [None] * target
        self._slot_restarts = [
            old_slot_restarts[p] if p < len(old_slot_restarts) else 0
            for p in range(target)
        ]
        self._rescale_target = None
        self._quiesced = set()
        print(
            f"pathway supervisor: rescaled {old} -> {target} in "
            f"{wall:.3f}s ({report or 'no reshard report'}); "
            "relaunching",
            file=sys.stderr,
        )
        for process_id in range(target):
            self._launch(process_id)
        return None

    # -- the supervision loop ------------------------------------------------

    def run(self) -> int:
        """Launch all workers and supervise until the mesh finishes or
        dies; returns the aggregated exit code (``cli.spawn`` convention,
        plus :data:`EXIT_LEADER_LOST` for an unrecovered leader loss)."""
        recovery = self.recovery
        print(
            f"Preparing {self.processes} process(es) "
            f"({self.processes * self.threads} total workers) "
            f"under supervision (recovery "
            f"{'on' if recovery else 'off'})",
            file=sys.stderr,
        )
        try:
            for process_id in range(self.processes):
                self._launch(process_id)
            while True:
                self._service_kill_requests()
                self._service_rescale_request()
                if (
                    self._rescale_target is not None
                    and _time.monotonic() > self._rescale_deadline
                ):
                    self._cancel_rescale(
                        "quiesce did not complete in time (is "
                        "persistence enabled?)"
                    )
                torn_down = False
                for process_id in range(self.processes):
                    if self._final_rc[process_id] is not None:
                        continue
                    handle = self._handles[process_id]
                    rc = None if handle is None else handle.poll()
                    if rc is None:
                        continue
                    if (
                        self._rescale_target is not None
                        and rc == EXIT_QUIESCED
                    ):
                        self._quiesced.add(process_id)
                        self._handles[process_id] = None
                        print(
                            f"pathway supervisor: worker {process_id} "
                            f"quiesced for rescale "
                            f"({len(self._quiesced)}/{self.processes})",
                            file=sys.stderr,
                        )
                        continue
                    if rc == EXIT_QUIESCED:
                        # stale quiesce: the rescale was aborted after the
                        # leader's quiesce command was already in flight,
                        # so this worker exited cleanly at a commit
                        # boundary for a rescale that no longer exists.
                        # It snapshotted before exiting — relaunch it
                        # (cold-restart path) without charging the
                        # restart budget.
                        print(
                            f"pathway supervisor: worker {process_id} "
                            "quiesced for an aborted rescale; "
                            "relaunching",
                            file=sys.stderr,
                        )
                        self._handles[process_id] = None
                        self._launch(process_id)
                        continue
                    if self._rescale_target is not None and rc != 0:
                        # a fault landed mid-quiesce: abort the rescale
                        # and let ordinary recovery handle this death
                        self._cancel_rescale(
                            f"worker {process_id} died (rc {rc}) "
                            "mid-quiesce"
                        )
                    # the leader is restartable only for SIGNAL deaths
                    # (kill/OOM/crash — the failover scenario); a clean
                    # non-zero leader exit is a program error and keeps
                    # the original propagation.  Followers restart for
                    # any non-zero death while the leader is still
                    # running.
                    leader_done = self._final_rc[0] is not None
                    if process_id == 0:
                        restartable = recovery and rc < 0
                    else:
                        restartable = (
                            recovery and rc != 0 and not leader_done
                        )
                    if not restartable:
                        self._final_rc[process_id] = rc
                        if process_id == 0 and rc < 0:
                            self._leader_lost = True
                            print(
                                f"pathway supervisor: leader died "
                                f"(rc {rc}) and recovery is off; "
                                f"surviving workers dump flight rings, "
                                f"then exit {EXIT_LEADER_LOST} "
                                "(leader lost)",
                                file=sys.stderr,
                            )
                        continue
                    if self.restarts >= self.max_restarts:
                        print(
                            f"pathway supervisor: worker {process_id} "
                            f"died (rc {rc}) with the restart budget "
                            f"exhausted ({self.max_restarts}); tearing "
                            "the mesh down",
                            file=sys.stderr,
                        )
                        self._final_rc[process_id] = rc
                        if process_id == 0:
                            self._leader_lost = True
                            print(
                                f"pathway supervisor: leader lost "
                                f"without restart budget; exit "
                                f"{EXIT_LEADER_LOST} after flight-dump "
                                "grace",
                                file=sys.stderr,
                            )
                            self._drain(8.0)
                        self._terminate_all()
                        torn_down = True
                        break
                    self.restarts += 1
                    self._slot_restarts[process_id] += 1
                    print(
                        f"pathway supervisor: worker {process_id} died "
                        f"(rc {rc}); restarting "
                        f"({self.restarts}/{self.max_restarts})",
                        file=sys.stderr,
                    )
                    self._launch(process_id)
                if torn_down:
                    for pid_, handle in enumerate(self._handles):
                        if self._final_rc[pid_] is None:
                            self._final_rc[pid_] = (
                                handle.returncode
                                if handle is not None
                                and handle.returncode is not None
                                else 1
                            )
                    break
                if (
                    self._rescale_target is not None
                    and len(self._quiesced) == self.processes
                ):
                    fatal = self._finish_rescale()
                    if fatal is not None:
                        for pid_ in range(self.processes):
                            if self._final_rc[pid_] is None:
                                self._final_rc[pid_] = fatal
                        break
                    continue
                if all(rc is not None for rc in self._final_rc):
                    break
                if self._final_rc[0] is not None:
                    # leader is done: give followers a moment to finish
                    # (and, on leader loss, to dump their flight rings),
                    # then stop waiting on them
                    self._drain(10.0)
                    self._terminate_all()
                    for pid_, handle in enumerate(self._handles):
                        if self._final_rc[pid_] is None:
                            self._final_rc[pid_] = (
                                handle.returncode
                                if handle is not None
                                and handle.returncode is not None
                                else 1
                            )
                    break
                _time.sleep(self.poll_interval)
        finally:
            self._terminate_all()
        if self._leader_lost:
            return EXIT_LEADER_LOST
        for rc in self._final_rc:
            if rc is None:
                return 1
            if rc != 0:
                return rc if rc > 0 else 128 - rc
        return 0
