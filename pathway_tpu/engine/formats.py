"""CDC / database parsers and formatters.

New implementations of the reference's Debezium message parser
(src/connectors/data_format.rs:1053 DebeziumMessageParser — Postgres and
MongoDB variants), the Postgres output formatters (PsqlUpdatesFormatter
:1625, PsqlSnapshotFormatter :1684) and a document formatter backing the
MongoDB/Elasticsearch writers (BsonFormatter :1975 analog; documents are
plain dicts here — the injected client is responsible for wire encoding).
"""

from __future__ import annotations

import json as _json
from typing import Any, Sequence

from pathway_tpu.engine.connectors import (
    DELETE,
    INSERT,
    UPSERT,
    ParsedEvent,
    Parser,
)
from pathway_tpu.engine.value import Json, Pointer

DEBEZIUM_EMPTY_KEY = "{}"


def _coerce_json(v: Any) -> Any:
    return Json(v) if isinstance(v, (dict, list)) else v


def _values_from_json(obj: Any, field_names: Sequence[str]) -> tuple:
    if not isinstance(obj, dict):
        raise ValueError(f"debezium: expected JSON object, got {obj!r}")
    return tuple(_coerce_json(obj.get(name)) for name in field_names)


class DebeziumParser(Parser):
    """Debezium CDC envelope parser.

    Payload is either a ``(key_bytes, value_bytes)`` pair (Kafka-shaped
    sources) or a single line ``key<separator>value`` (file-based tests,
    like the reference's RawBytes branch). Operations:

    - postgres: ``r``/``c`` -> insert(after); ``u`` -> delete(before) +
      insert(after); ``d`` -> delete(before). Native session.
    - mongodb: ``r``/``c``/``u`` -> upsert(after); ``d`` -> upsert(key,
      None). Upsert session (Mongo change events lack the prior state).

    Reference: data_format.rs:1053-1439.
    """

    def __init__(
        self,
        value_field_names: Sequence[str],
        key_field_names: Sequence[str] | None = None,
        db_type: str = "postgres",
        separator: str = "\t",
    ) -> None:
        super().__init__(value_field_names)
        if db_type not in ("postgres", "mongodb"):
            raise ValueError(f"unknown debezium db_type {db_type!r}")
        self.key_field_names = list(key_field_names) if key_field_names else None
        self.db_type = db_type
        self.separator = separator
        self.session_type = "native" if db_type == "postgres" else "upsert"

    def _key_of(self, key_payload: Any) -> tuple | None:
        if self.key_field_names is None:
            return None
        return _values_from_json(key_payload, self.key_field_names)

    def parse(self, payload: Any) -> list[ParsedEvent]:
        if isinstance(payload, tuple):
            raw_key, raw_value = payload
        else:
            if isinstance(payload, bytes):
                payload = payload.decode("utf-8")
            parts = payload.strip().split(self.separator)
            if len(parts) != 2:
                raise ValueError(
                    f"debezium: expected key{self.separator!r}value, got "
                    f"{len(parts)} tokens"
                )
            raw_key, raw_value = parts
        if isinstance(raw_key, bytes):
            raw_key = raw_key.decode("utf-8")
        if isinstance(raw_value, bytes):
            raw_value = raw_value.decode("utf-8")
        if raw_key is None:
            if self.key_field_names is not None:
                raise ValueError("debezium: empty kafka key payload")
            raw_key = DEBEZIUM_EMPTY_KEY
        if raw_value is None:
            return []  # kafka tombstone

        value_change = _json.loads(raw_value)
        if value_change is None:
            return []  # tombstone event
        if not isinstance(value_change, dict) or "payload" not in value_change:
            raise ValueError("debezium: no payload at the top level")
        change = value_change["payload"]
        key_change = _json.loads(raw_key)
        key_payload = (
            key_change.get("payload") if isinstance(key_change, dict) else None
        )
        key = self._key_of(key_payload)

        op = change.get("op")
        events: list[ParsedEvent] = []
        if op in ("r", "c"):
            after = _values_from_json(change.get("after"), self.column_names)
            kind = INSERT if self.db_type == "postgres" else UPSERT
            events.append(ParsedEvent(kind, after, key=key))
        elif op == "u":
            if self.db_type == "postgres":
                before = _values_from_json(
                    change.get("before"), self.column_names
                )
                after = _values_from_json(change.get("after"), self.column_names)
                events.append(ParsedEvent(DELETE, before, key=key))
                events.append(ParsedEvent(INSERT, after, key=key))
            else:
                after = _values_from_json(change.get("after"), self.column_names)
                events.append(ParsedEvent(UPSERT, after, key=key))
        elif op == "d":
            if self.db_type == "postgres":
                before = _values_from_json(
                    change.get("before"), self.column_names
                )
                events.append(ParsedEvent(DELETE, before, key=key))
            else:
                events.append(ParsedEvent(UPSERT, None, key=key))
        else:
            raise ValueError(f"debezium: unsupported operation {op!r}")
        return events


# -- SQL statement formatters -------------------------------------------------


def _sql_value(v: Any) -> Any:
    if isinstance(v, Json):
        return _json.dumps(v.value)
    if isinstance(v, Pointer):
        return repr(v)
    return v


class PsqlUpdatesFormatter:
    """Append-only update log: every change becomes an INSERT carrying
    (values..., time, diff) (reference PsqlUpdatesFormatter
    data_format.rs:1625). ``format`` returns (statement, params)."""

    def __init__(self, table_name: str, value_field_names: Sequence[str]) -> None:
        self.table_name = table_name
        self.value_field_names = list(value_field_names)

    def format(
        self, key: Pointer, values: tuple, time: int, diff: int
    ) -> tuple[str, list]:
        if len(values) != len(self.value_field_names):
            raise ValueError("column/value count mismatch")
        placeholders = ",".join(
            f"${i}" for i in range(1, len(values) + 1)
        )
        stmt = (
            f"INSERT INTO {self.table_name} "
            f"({','.join(self.value_field_names)},time,diff) "
            f"VALUES ({placeholders},{time},{diff})"
        )
        return stmt, [_sql_value(v) for v in values]


class PsqlSnapshotFormatter:
    """Maintain the output table as a snapshot: inserts become upserts
    (INSERT ... ON CONFLICT (keys) DO UPDATE), deletions become DELETEs by
    key (reference PsqlSnapshotFormatter data_format.rs:1684)."""

    def __init__(
        self,
        table_name: str,
        key_field_names: Sequence[str],
        value_field_names: Sequence[str],
    ) -> None:
        positions: dict[str, int] = {}
        for idx, name in enumerate(value_field_names):
            if name in positions:
                raise ValueError(f"repeated value field {name!r}")
            positions[name] = idx
        self.key_field_positions: list[int] = []
        for name in key_field_names:
            if name not in positions:
                raise ValueError(f"unknown key field {name!r}")
            self.key_field_positions.append(positions.pop(name))
        self.value_field_positions = sorted(positions.values())
        self.key_field_positions.sort()
        self.table_name = table_name
        self.key_field_names = list(key_field_names)
        self.value_field_names = list(value_field_names)

    def format(
        self, key: Pointer, values: tuple, time: int, diff: int
    ) -> tuple[str, list]:
        if len(values) != len(self.value_field_names):
            raise ValueError("column/value count mismatch")
        if diff > 0:
            placeholders = ",".join(
                f"${i}" for i in range(1, len(values) + 1)
            )
            set_items = [
                f"{self.value_field_names[p]}=${p + 1}"
                for p in self.value_field_positions
            ] + [f"time={time}", f"diff={diff}"]
            condition = " AND ".join(
                f"{self.table_name}.{self.value_field_names[p]}=${p + 1}"
                for p in self.key_field_positions
            )
            stmt = (
                f"INSERT INTO {self.table_name} "
                f"({','.join(self.value_field_names)},time,diff) "
                f"VALUES ({placeholders},{time},{diff}) "
                f"ON CONFLICT ({','.join(self.key_field_names)}) "
                f"DO UPDATE SET {','.join(set_items)} "
                f"WHERE {condition}"
            )
            return stmt, [_sql_value(v) for v in values]
        params = [
            _sql_value(values[p]) for p in self.key_field_positions
        ]
        condition = " AND ".join(
            f"{self.value_field_names[p]}=${i + 1}"
            for i, p in enumerate(self.key_field_positions)
        )
        return f"DELETE FROM {self.table_name} WHERE {condition}", params


class DocumentFormatter:
    """Row -> plain-dict document with time/diff fields; backs the MongoDB
    and Elasticsearch writers (reference BsonFormatter data_format.rs:1975,
    JsonLines for ES :1822)."""

    def __init__(self, value_field_names: Sequence[str]) -> None:
        self.value_field_names = list(value_field_names)

    def format(self, key: Pointer, values: tuple, time: int, diff: int) -> dict:
        doc = {}
        for name, v in zip(self.value_field_names, values):
            if isinstance(v, Json):
                v = v.value
            elif isinstance(v, Pointer):
                v = repr(v)
            doc[name] = v
        doc["time"] = time
        doc["diff"] = diff
        return doc
