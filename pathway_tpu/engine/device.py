"""Columnar device bridge: column-major batches + vectorized evaluation.

This is the engine's answer to the reference's native columnar hot path
(reference: src/engine/dataflow.rs — tables as differential collections
processed in Rust). Here large commits are processed column-at-a-time:

- :class:`ColumnarView` materialises a column-major, NumPy-backed view of a
  batch's inserted rows. Extraction is lazy per column and falls back (to
  the per-row interpreter) whenever a column is not a clean homogeneous
  numeric/bool/string sequence — so ERROR poisoning, ``None`` handling and
  arbitrary Python values keep their exact row-wise semantics.
- :func:`eval_columnar` evaluates an engine expression tree over a view in
  whole-column NumPy ops (the batch-wise fast path promised by
  engine/expression.py's module docstring).
- :func:`to_device` hands a column to ``jax.Array`` zero-copy (dlpack path
  for aligned arrays); this is how numeric columns ride to TPU HBM without
  a Python-tuple detour (BASELINE's "zero-copy bridge").
- :func:`factorize` / :func:`segment_sum` back the vectorized groupby
  (engine/graph.py GroupbyNode): per-row work collapses to one
  ``np.unique`` + one segment reduction, leaving only per-*group* Python.

Integer semantics note: the vectorized path computes in int64, which is the
reference engine's integer type as well (Value::Int is i64,
src/engine/value.rs:207) — Python bigints beyond int64 fall back to the
row-wise interpreter at extraction time (OverflowError → object dtype).
"""

from __future__ import annotations

import weakref
from typing import Any, Sequence

import numpy as np

from pathway_tpu.engine import expression as ex
from pathway_tpu.native import kernels as _native

# Batches smaller than this are cheaper to run through the per-row
# interpreter than to columnarise.
VECTOR_THRESHOLD = 256

_OK_KINDS = frozenset("bifU")


class ColumnarView:
    """Lazy column-major view over a batch's rows (insertions only).

    ``from_entries=True`` views ``(key, row, diff)`` entries directly —
    saving the 1M-element row list comprehension on the hot paths."""

    __slots__ = ("rows", "n", "_cols", "_entries")

    def __init__(
        self, rows: Sequence[tuple], from_entries: bool = False
    ) -> None:
        self.rows = rows
        self.n = len(rows)
        self._cols: dict[int, np.ndarray | None] = {}
        self._entries = from_entries

    def column(self, index: int) -> np.ndarray | None:
        """The column as a NumPy array, or None if not cleanly columnar
        (mixed types, None/ERROR values, nested containers, bigints)."""
        got = self._cols.get(index, _MISSING)
        if got is not _MISSING:
            return got
        arr = None
        if _native is not None and isinstance(self.rows, list):
            # one C pass for int64/float64/bool columns; returns None for
            # strings and anything non-clean (falls through below)
            arr = _native.extract_column(self.rows, index, self._entries)
        if arr is None:
            values = (
                [e[1][index] for e in self.rows]
                if self._entries
                else [row[index] for row in self.rows]
            )
            arr = _extract(values)
        self._cols[index] = arr
        return arr


def materialize_columns(view: ColumnarView, arity: int) -> list[np.ndarray]:
    """Every column of an entry view as an array — clean dtypes where
    extraction succeeds, an exact-object array otherwise (never None).
    Object columns keep the original Python values, so a row round-trip
    through ``Columns.to_entries`` is lossless."""
    cols = []
    rows = view.rows
    for c in range(arity):
        col = view.column(c)
        if col is None:
            arr = np.empty(view.n, object)
            arr[:] = (
                [e[1][c] for e in rows]
                if view._entries
                else [r[c] for r in rows]
            )
            col = arr
        cols.append(col)
    return cols


_MISSING = object()


class PayloadView:
    """ColumnarView-compatible adapter over a columnar batch payload
    (engine/batch.py Columns): columns are already arrays, so extraction
    is a dtype screen, not a per-row pass."""

    __slots__ = ("_payload", "n")

    def __init__(self, payload: Any) -> None:
        self._payload = payload
        self.n = payload.n

    def column(self, index: int) -> np.ndarray | None:
        col = self._payload.cols[index]
        return col if col.dtype.kind in _OK_KINDS else None


def _extract(values: list) -> np.ndarray | None:
    """list of Python scalars -> homogeneous ndarray, else None."""
    kinds = set(map(type, values))
    if not kinds or not kinds.issubset(_CLEAN_TYPES):
        return None
    if len(kinds) > 1:
        # int+float mixing would silently promote ints in passthrough
        # columns; bool+int would demote. Keep exact dtypes only.
        return None
    if next(iter(kinds)) is str and any("\x00" in v for v in values):
        # NumPy U-dtype strips trailing NULs on round-trip
        return None
    try:
        arr = np.asarray(values)
    except (OverflowError, ValueError):
        return None
    if arr.dtype == object or arr.dtype.kind not in _OK_KINDS:
        return None
    return arr


_CLEAN_TYPES = frozenset((int, float, bool, str))


class NotVectorizable(Exception):
    """Raised when an expression (or its operand columns) can't run
    column-wise; the caller falls back to the row interpreter."""


# Ops where NumPy semantics diverge from the per-row interpreter on edge
# inputs (ZeroDivisionError -> ERROR poisoning vs inf/nan; 0**-1 etc.).
_DIVISION_OPS = frozenset(("/", "//", "%"))

_I64_MAX = (1 << 63) - 1


def _guard_int_overflow(op: str, a: np.ndarray, b: np.ndarray) -> None:
    """int64 wraps silently in NumPy while the row interpreter computes exact
    Python ints — reject any int op whose result could leave int64 range.
    Conservative magnitude bounds (exact Python-int arithmetic, O(n) maxes)."""
    if op not in ("+", "-", "*", "**", "<<"):
        return  # //, %, comparisons, bitwise cannot exceed operand magnitude
    amax = int(np.abs(a).max(initial=0))
    bmax = int(np.abs(b).max(initial=0))
    if amax < 0 or bmax < 0:  # np.abs(INT64_MIN) wraps negative
        raise NotVectorizable(f"possible int64 overflow in {op}")
    if op in ("+", "-"):
        safe = amax + bmax <= _I64_MAX
    elif op == "*":
        safe = amax * bmax <= _I64_MAX
    elif op == "**":
        safe = bmax <= 63 and (amax <= 1 or amax.bit_length() * bmax <= 63)
    else:  # <<
        safe = bmax <= 62 and amax.bit_length() + bmax <= 63
    if not safe:
        raise NotVectorizable(f"possible int64 overflow in {op}")


def eval_columnar(expr: ex.EngineExpression, view: ColumnarView) -> np.ndarray:
    """Evaluate ``expr`` over all rows at once. Raises NotVectorizable when
    any sub-expression or operand column requires row-wise treatment."""
    if isinstance(expr, ex.ColumnRef):
        col = view.column(expr.index)
        if col is None:
            raise NotVectorizable(f"column {expr.index}")
        return col
    if isinstance(expr, ex.Const):
        v = expr.value
        if type(v) not in _CLEAN_TYPES:
            raise NotVectorizable("const")
        return np.broadcast_to(np.asarray(v), (view.n,))
    if isinstance(expr, ex.Binary):
        if expr.op == "@":
            raise NotVectorizable("matmul")
        a = eval_columnar(expr.left, view)
        b = eval_columnar(expr.right, view)
        if expr.op in _DIVISION_OPS:
            if b.dtype.kind not in "bif" or not np.all(b):
                raise NotVectorizable("division edge case")
        if expr.op == "**":
            if a.dtype.kind == "i" and (b.dtype.kind != "i" or np.any(b < 0)):
                raise NotVectorizable("pow edge case")
        if a.dtype.kind == "U" or b.dtype.kind == "U":
            if a.dtype.kind != b.dtype.kind:
                raise NotVectorizable("string vs non-string operands")
            if expr.op not in ("==", "!=", "<", "<=", ">", ">=", "+"):
                raise NotVectorizable("string op")
            if expr.op == "+":
                return np.char.add(a, b)
        if expr.op in ("+", "-", "*", "**", "//", "%") and (
            a.dtype.kind == "b" or b.dtype.kind == "b"
        ):
            # NumPy bool arithmetic (e.g. True+True=True) diverges from
            # Python's int promotion (True+True=2)
            raise NotVectorizable("bool arithmetic")
        if a.dtype.kind == "i" and b.dtype.kind == "i":
            _guard_int_overflow(expr.op, a, b)
        try:
            with np.errstate(all="raise"):
                return expr.fn(a, b)
        except Exception as e:  # noqa: BLE001 — row path owns error semantics
            raise NotVectorizable(str(e)) from None
    if isinstance(expr, ex.Unary):
        a = eval_columnar(expr.arg, view)
        if expr.op == "not":
            if a.dtype.kind != "b":
                raise NotVectorizable("not on non-bool")
            return ~a
        if expr.op == "~" and a.dtype.kind == "b":
            return ~a
        try:
            with np.errstate(all="raise"):
                return expr.fn(a)
        except Exception as e:  # noqa: BLE001
            raise NotVectorizable(str(e)) from None
    if isinstance(expr, ex.BooleanChain):
        parts = [eval_columnar(arg, view) for arg in expr.args]
        for p in parts:
            if p.dtype.kind != "b":
                raise NotVectorizable("boolean chain on non-bool")
        fn = np.logical_and if expr.op == "and" else np.logical_or
        out = parts[0]
        for p in parts[1:]:
            out = fn(out, p)
        return out
    if isinstance(expr, ex.IfElse):
        c = eval_columnar(expr.cond, view)
        if c.dtype.kind != "b":
            raise NotVectorizable("if_else condition not bool")
        t = eval_columnar(expr.then, view)
        f = eval_columnar(expr.otherwise, view)
        if t.dtype != f.dtype:
            raise NotVectorizable("if_else branch dtype mismatch")
        return np.where(c, t, f)
    if isinstance(expr, ex.IsNone):
        # a successfully extracted column holds no Nones by construction
        eval_columnar(expr.arg, view)
        val = bool(expr.negated)
        return np.broadcast_to(np.asarray(val), (view.n,))
    raise NotVectorizable(type(expr).__name__)


def eval_expressions_columnar_cols(
    expressions: Sequence[ex.EngineExpression],
    rows: Sequence[tuple],
    from_entries: bool = False,
) -> list[list] | None:
    """Vectorized ExpressionNode body: all expressions over all rows,
    returned column-major as plain Python lists (exact interpreter types).
    None signals fallback to the row interpreter."""
    view = ColumnarView(rows, from_entries=from_entries)
    outs = []
    for expr in expressions:
        try:
            arr = eval_columnar(expr, view)
        except NotVectorizable:
            return None
        outs.append(np.ascontiguousarray(arr).tolist())
    return outs


def eval_expressions_columnar(
    expressions: Sequence[ex.EngineExpression], rows: Sequence[tuple]
) -> list[tuple] | None:
    """Row-major variant of :func:`eval_expressions_columnar_cols`."""
    outs = eval_expressions_columnar_cols(expressions, rows)
    if outs is None:
        return None
    return list(zip(*outs))


# -- groupby acceleration ----------------------------------------------------


def factorize(values: np.ndarray) -> tuple[list, np.ndarray]:
    """Distinct values + the inverse index of each row's group."""
    uniques, inverse = np.unique(values, return_inverse=True)
    return uniques.tolist(), inverse


def factorize_multi(
    arrays: "list[np.ndarray]",
) -> tuple[np.ndarray, np.ndarray]:
    """Composite factorization over several same-length columns:
    ``(first, inverse)`` where ``first[g]`` is a representative row index
    of distinct tuple ``g`` and ``inverse[i]`` is row ``i``'s tuple id.

    Tuple identity is reduced to integer-code identity column by column:
    per-column dense codes (``np.unique``) chain through a mixed-radix
    combine, re-densified each step so codes stay ``< n**2`` and the
    int64 product cannot overflow. No Python tuples are materialised.
    """
    combined: np.ndarray | None = None
    for a in arrays:
        _u, inv = np.unique(a, return_inverse=True)
        inv = inv.astype(np.int64, copy=False).reshape(-1)
        if combined is None:
            combined = inv
        else:
            _pu, prev = np.unique(combined, return_inverse=True)
            combined = prev.astype(np.int64).reshape(-1) * np.int64(
                len(_u)
            ) + inv
    assert combined is not None
    _uc, first, inverse = np.unique(
        combined, return_index=True, return_inverse=True
    )
    return first, inverse.reshape(-1)


def segment_count(
    inverse: np.ndarray, diffs: np.ndarray, n_groups: int
) -> np.ndarray:
    """Per-group sum of diffs (int64-exact)."""
    out = np.zeros(n_groups, np.int64)
    np.add.at(out, inverse, diffs)
    return out


def int_sum_overflow_risk(col: np.ndarray, n: int, dmax: int) -> bool:
    """True when an int64 segment sum of ``col`` (diff magnitudes up to
    ``dmax`` over ``n`` rows) could leave int64 range — the vectorized
    paths compute in wrapping int64 while the row interpreter uses exact
    Python ints, so risky batches must take the row path."""
    if col.dtype.kind != "i" or col.size == 0:
        return False
    amax = int(np.abs(col).max())
    if amax < 0 or dmax < 0:  # np.abs(INT64_MIN) wraps negative
        return True
    return amax * n * dmax > (1 << 62)


def segment_sum(
    inverse: np.ndarray,
    values: np.ndarray,
    diffs: np.ndarray,
    n_groups: int,
) -> np.ndarray:
    """Per-group sum of value*diff; int64-exact for int/bool inputs."""
    if values.dtype.kind in "ib":
        out = np.zeros(n_groups, np.int64)
        np.add.at(out, inverse, values.astype(np.int64) * diffs)
        return out
    return np.bincount(
        inverse, weights=values * diffs, minlength=n_groups
    )


# -- zero-copy device hand-off ----------------------------------------------


_DEVICE_COUNT: int | None = None


def device_count() -> int:
    """Visible JAX devices, cached; 0 when jax is unavailable — never
    raises.  Mesh-detection gates (collective exchange's one-device-per-
    shard rule) call this on delivery hot paths, so the probe runs once."""
    global _DEVICE_COUNT
    if _DEVICE_COUNT is None:
        try:
            import jax

            _DEVICE_COUNT = len(jax.devices())
        except Exception:
            _DEVICE_COUNT = 0
    return _DEVICE_COUNT


def to_device(arr: np.ndarray, sharding: Any | None = None):
    """NumPy column -> jax.Array, zero-copy where the backend allows (CPU
    dlpack aliasing; on TPU this is the single necessary host->HBM DMA).

    Counted on the ``pathway_device_transfer_*`` ledger in both modes —
    zero-copy backends over-count by the aliased bytes, which is the
    conservative direction for the transfer-reduction gates."""
    import jax

    from pathway_tpu.engine import device_residency as _dres

    _dres.record_h2d(int(getattr(arr, "nbytes", 0)))
    if sharding is not None:
        return jax.device_put(arr, sharding)
    return jax.numpy.asarray(arr)


def rows_to_device_matrix(rows: Sequence[tuple], col: int, dtype=np.float32):
    """Stack a vector-valued column ([dim]-tuples/ndarrays) into one [n, dim]
    device array — the ingest feed for the HBM KNN index."""
    mat = np.asarray([np.asarray(r[col], dtype) for r in rows], dtype)
    return to_device(mat)


# -- device-resident row cells ------------------------------------------------

#: device batches produced since the last commit boundary (weak: a batch
#: no row references anymore needs no decay)
_LIVE_HANDLES: "weakref.WeakSet" = weakref.WeakSet()


def _identity(arr: np.ndarray) -> np.ndarray:
    return arr


class DeviceBatchHandle:
    """A ``[n, dim]`` device array with a lazily-downloaded host twin —
    produced by device UDF batches (the embedder), consumed directly by
    device operators (the HBM index) without a host round trip.

    Lifecycle: within the producing commit BOTH copies may exist — a
    subscribe callback materialising the host twin must not steal the
    device copy from an index operator later in the same sweep. At
    commit end the scheduler calls :func:`decay_device_batches`, which
    downloads any still-live batch (the DMA was prefetched, so this is a
    cheap wait) and releases its HBM. HBM usage is therefore bounded by
    one commit's worth of batches; rows retained in table state hold
    only the host twin — the same RAM the eager path used.
    """

    __slots__ = ("dev", "_host", "_prefetched", "__weakref__")

    def __init__(self, dev: Any) -> None:
        self.dev = dev
        self._host = None
        self._prefetched = False
        _LIVE_HANDLES.add(self)

    def prefetch(self) -> None:
        """Start the device→host DMA without blocking. ``host()`` later
        completes against the cached buffer instead of paying a full
        synchronous round trip — over remote-device links this turns a
        ~100 ms stall per batch into background transfer that overlaps
        the next batch's tokenize+dispatch."""
        if self._host is None and not self._prefetched:
            copy_async = getattr(self.dev, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
            self._prefetched = True

    def host(self) -> np.ndarray:
        if self._host is None:
            from pathway_tpu.engine import device_residency as _dres

            self._host = np.asarray(self.dev)
            _dres.record_d2h(int(self._host.nbytes))
        return self._host

    def decay(self) -> None:
        """Materialise the host twin and release the HBM copy."""
        if self.dev is not None:
            self.prefetch()
            self.host()
            self.dev = None


def decay_device_batches() -> None:
    """Synchronous end-of-commit hook: download + release all device
    batches produced this commit. Keeps HBM bounded by one commit while
    letting any device operator in the commit consume the batch
    transfer-free regardless of sweep order. This is the bit-exact spec
    the async pipeline (engine/device_pipeline.py) is measured against;
    schedulers now route the boundary through
    ``device_pipeline.commit_boundary`` which falls back to this
    behaviour under ``PATHWAY_TPU_ASYNC_DEVICE=0``."""
    if _LIVE_HANDLES:
        for handle in list(_LIVE_HANDLES):
            handle.decay()
        _LIVE_HANDLES.clear()


def stage_device_batches() -> list:
    """Detach and return this commit's live device batches without
    decaying them — the async pipeline's staging primitive. The caller
    (``DevicePipeline.commit_boundary``) owns completion; the WeakSet is
    cleared so the next commit accumulates a fresh generation. Returns
    ``[]`` on host-only commits, making the boundary near-free."""
    if not _LIVE_HANDLES:
        return []
    handles = list(_LIVE_HANDLES)
    _LIVE_HANDLES.clear()
    return handles


class LazyDeviceVector:
    """One row of a DeviceBatchHandle. Behaves like the host ndarray on any
    host-side use (``__array__`` downloads the parent batch once), while
    device consumers slice ``batch.dev`` with zero transfers.

    Like ndarrays, instances are unhashable and compare elementwise, so the
    engine's consolidation/diff fallbacks treat them identically.
    """

    __slots__ = ("batch", "index")

    def __init__(self, batch: DeviceBatchHandle, index: int) -> None:
        self.batch = batch
        self.index = index

    # -- host materialisation -------------------------------------------------

    def __array__(self, dtype: Any = None, copy: Any = None) -> np.ndarray:
        row = self.batch.host()[self.index]
        if dtype is not None and row.dtype != dtype:
            row = row.astype(dtype)
        return np.array(row, copy=True) if copy else row

    def _parent_array(self) -> Any:
        dev = self.batch.dev
        return dev if dev is not None else self.batch.host()

    @property
    def shape(self) -> tuple:
        return tuple(self._parent_array().shape[1:])

    @property
    def dtype(self) -> Any:
        return np.dtype(str(self._parent_array().dtype))

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def reshape(self, *shape: Any) -> np.ndarray:
        return np.asarray(self).reshape(*shape)

    def __len__(self) -> int:
        return self.shape[0]

    def __iter__(self):
        return iter(np.asarray(self))

    def __getitem__(self, item: Any) -> Any:
        return np.asarray(self)[item]

    def __eq__(self, other: Any) -> Any:
        return np.asarray(self) == other

    def __ne__(self, other: Any) -> Any:
        return np.asarray(self) != other

    __hash__ = None  # type: ignore[assignment]  # like np.ndarray

    def __repr__(self) -> str:
        return repr(np.asarray(self))

    def __reduce__(self):
        return (_identity, (np.array(np.asarray(self)),))


def lazy_rows(dev_batch: Any, n: int, prefetch: bool = True) -> list:
    """Wrap a device ``[b, dim]`` result as ``n`` lazy per-row cells.

    ``prefetch`` starts the host copy in the background immediately: the
    device consumers (HBM index) slice ``dev`` regardless, and any host
    consumer (subscribe callbacks, persistence) finds the bytes already
    in flight."""
    handle = DeviceBatchHandle(dev_batch)
    if prefetch:
        handle.prefetch()
    return [LazyDeviceVector(handle, i) for i in range(n)]


def device_runs(
    vectors: Sequence[Any],
) -> list[tuple[int, int, Any, list[int] | None]]:
    """Partition ``vectors`` into maximal contiguous runs of
    ``(start, stop, dev_array_or_None, row_indices_or_None)``.

    A run with a device array means every vector in it is a
    LazyDeviceVector of that one live batch — consumable by device
    operators with a transfer-free gather. A ``None`` run is host data.
    Batch-executor chunking makes several parents per commit the normal
    case, so callers should iterate runs rather than requiring a single
    common parent."""
    runs: list[tuple[int, int, Any, list[int] | None]] = []
    i, n = 0, len(vectors)
    while i < n:
        v = vectors[i]
        if isinstance(v, LazyDeviceVector) and v.batch.dev is not None:
            parent = v.batch
            indices = [v.index]
            j = i + 1
            while (
                j < n
                and isinstance(vectors[j], LazyDeviceVector)
                and vectors[j].batch is parent
            ):
                indices.append(vectors[j].index)
                j += 1
            runs.append((i, j, parent.dev, indices))
        else:
            j = i + 1
            while j < n and not (
                isinstance(vectors[j], LazyDeviceVector)
                and vectors[j].batch.dev is not None
            ):
                j += 1
            runs.append((i, j, None, None))
        i = j
    return runs


def common_device_parent(vectors: Sequence[Any]) -> tuple[Any, list[int]] | None:
    """When every vector is a LazyDeviceVector of one live batch, return
    (device array, row indices) for a transfer-free gather. Thin shim over
    :func:`device_runs` so liveness semantics live in one place."""
    runs = device_runs(list(vectors))
    if len(runs) == 1 and runs[0][2] is not None:
        return runs[0][2], runs[0][3]
    return None
