"""Async device pipeline: double-buffered commit staging between the host
dataflow and device work.

The synchronous engine serializes host and device per commit: every
scheduler sweep ends in :func:`~pathway_tpu.engine.device.decay_device_batches`,
a blocking device->host download of every device batch the commit
produced, so connector ingest for commit N+1 cannot start until commit
N's device work (embed dispatch, index scatter, D2H DMA) has fully
retired.  On the streaming RAG bench that barrier is most of the ~20x
gap between `pw.run` throughput and the device embed ceiling.

This module turns the barrier into a pipeline stage:

- **staging queue (host->device)** — at each commit boundary the
  scheduler hands the commit's live :class:`DeviceBatchHandle` set to
  :meth:`DevicePipeline.commit_boundary` instead of decaying it inline.
  The handles' D2H DMA is *started* (``copy_to_host_async``) but not
  awaited; the host thread returns to the connector poll loop and
  ingests commit N+1 while the device crunches commit N.  jax dispatch
  stays async end to end — the only ``block_until_ready``-equivalent
  wait is the completion worker's ``decay()``.
- **completion queue (device->host)** — a single daemon worker pops
  staged commits strictly FIFO and completes them (awaits the DMA,
  releases HBM), so commit completion is **in order** by construction:
  commit N's device effects are fully host-resident before commit N+1's
  are.  Exactly-once/checkpoint semantics are preserved by the runner
  calling :meth:`drain_until` before persistence/snapshot ``on_commit``
  hooks — a checkpoint for commit N can only be cut after N completed.
- **double buffering / backpressure** — at most ``depth`` commits
  (default 2, ``PATHWAY_TPU_DEVICE_INFLIGHT``) may be in flight;
  staging commit N+depth blocks until commit N retires, bounding HBM to
  ``depth`` commits' worth of batches (the sync path bounds it to 1).
- **feedback-driven batch sizing** — :class:`AdaptiveBatchController`
  reads the PR-5 queue-depth gauge and the PR-8 critical-path buckets
  each device commit and adapts the device micro-batch size (consumed
  by ``BatchExecutor`` via :func:`suggested_batch_size`) and the
  connector autocommit window scale (:func:`ingest_window_scale`,
  consumed by ``InputDriver.effective_autocommit_s``): when the device
  stage is the bottleneck it grows batches/windows to amortize dispatch,
  when the host residual dominates it shrinks them to start overlap
  earlier — TeleRAG-style lookahead, driven by measurement instead of
  a static schedule.

``PATHWAY_TPU_ASYNC_DEVICE=0`` is the escape hatch: the commit boundary
then decays inline, bit-identical to the pre-pipeline engine (PR-2
style: the synchronous path stays the spec; tests/test_device_pipeline.py
holds the two modes to bit-identical sinks on all three schedulers).

Occupancy is first-class: ``pathway_device_queue_depth`` (staged +
in-completion commits), ``pathway_device_occupancy_ratio`` (EMA share
of wall time the completion stage is busy), and the
``pathway_device_dispatch_complete_seconds`` histogram (commit-boundary
dispatch -> completion retire latency) all live on the PR-5 registry,
so they ride the mesh snapshot piggyback to the leader ``/metrics``
and render in ``cli stats``.
"""

from __future__ import annotations

import os
import threading
import time as _time
from collections import deque

from pathway_tpu.internals import metrics as _metrics
from pathway_tpu.internals import tracing as _tracing

__all__ = [
    "AdaptiveBatchController",
    "DevicePipeline",
    "PIPELINE",
    "async_enabled",
    "commit_boundary",
    "drain",
    "drain_until",
    "reset",
    "suggested_batch_size",
    "ingest_window_scale",
]

#: dispatch->complete latency bucket bounds, seconds — device commits
#: retire in the 100us..1s band on live hardware, slower over remote links
DISPATCH_BUCKETS = (
    0.0001,
    0.00025,
    0.0005,
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
)


def async_enabled() -> bool:
    """The escape hatch: ``PATHWAY_TPU_ASYNC_DEVICE=0`` restores the
    synchronous inline-decay commit boundary (the bit-exact spec)."""
    return os.environ.get("PATHWAY_TPU_ASYNC_DEVICE", "1").lower() not in (
        "0",
        "false",
        "no",
    )


def _env_int(name: str, default: int, floor: int = 1) -> int:
    try:
        return max(floor, int(os.environ.get(name, str(default))))
    except ValueError:
        return default


class AdaptiveBatchController:
    """Feedback loop closing PRs 5-8's measurement machinery into sizing.

    Inputs, read once per *device* commit (host-only commits never touch
    the controller):

    - pipeline pressure — staged depth and whether staging had to block
      on the in-flight bound (the device stage is saturated);
    - completion-stage occupancy (EMA, 0..1);
    - the host queue-depth gauge (``pathway_queue_depth``, PR 5);
    - the last sampled commit's critical-path buckets (PR 8), when
      tracing is on — ``host_compute_s`` vs ``device_s`` decides which
      side of the pipe is the bottleneck when occupancy is ambiguous.

    Outputs:

    - ``batch_size`` — suggested device micro-batch rows; consumed by
      ``BatchExecutor`` (it only ever *narrows* the user's configured
      ``max_batch_size``, never exceeds it);
    - ``depth`` — staged-commit bound (double buffering by default);
    - ``window_scale`` — multiplier on connector autocommit windows
      (1.0..4.0): a saturated device stage wants fewer, fatter commits.

    The rules are deliberately monotone and clamped so the loop cannot
    oscillate unboundedly: saturation doubles the batch and widens the
    window; an idle completion stage with a host-bound critical path
    halves the batch and narrows the window back toward 1.0.
    """

    #: occupancy below which the device stage counts as starved
    IDLE_OCCUPANCY = 0.25

    def __init__(self) -> None:
        self.min_batch = _env_int("PATHWAY_TPU_DEVICE_BATCH_MIN", 32)
        self.max_batch = _env_int("PATHWAY_TPU_DEVICE_BATCH_MAX", 65536)
        self.batch_size = _env_int(
            "PATHWAY_TPU_DEVICE_BATCH", 1024, floor=self.min_batch
        )
        self.depth = _env_int("PATHWAY_TPU_DEVICE_INFLIGHT", 2)
        self.window_scale = 1.0
        self.ticks = 0
        self.grows = 0
        self.shrinks = 0
        self._queue_gauge = None

    def _host_queue_depth(self) -> float:
        g = self._queue_gauge
        if g is None:
            g = self._queue_gauge = _metrics.REGISTRY.gauge(
                "pathway_queue_depth",
                "operators with pending delta batches (backpressure)",
            )
        return g.value

    @staticmethod
    def _last_critical_path() -> dict | None:
        if not _tracing.TRACER.enabled:
            return None
        traces = _tracing.TRACER.traces()
        return traces[-1]["critical_path"] if traces else None

    def observe(
        self, *, staged_depth: int, blocked: bool, occupancy: float
    ) -> None:
        """One device-commit tick of the feedback loop."""
        self.ticks += 1
        if blocked or staged_depth >= self.depth:
            # the completion stage is the bottleneck: amortize dispatch
            # with fatter device batches and fewer, larger commits
            self.batch_size = min(self.max_batch, self.batch_size * 2)
            self.window_scale = min(4.0, self.window_scale * 1.25)
            self.grows += 1
            return
        if occupancy < self.IDLE_OCCUPANCY:
            cp = self._last_critical_path()
            host_bound = cp is None or cp.get("host_compute_s", 0.0) >= cp.get(
                "device_s", 0.0
            )
            if host_bound and self._host_queue_depth() >= 0.0:
                # device starved while the host sweats: smaller batches
                # reach the device sooner, and the ingest window relaxes
                # back toward its configured value
                if self.batch_size > self.min_batch:
                    self.batch_size = max(
                        self.min_batch, self.batch_size // 2
                    )
                    self.shrinks += 1
                self.window_scale = max(1.0, self.window_scale / 1.25)

    def stats(self) -> dict:
        return {
            "batch_size": self.batch_size,
            "depth": self.depth,
            "window_scale": round(self.window_scale, 3),
            "ticks": self.ticks,
            "grows": self.grows,
            "shrinks": self.shrinks,
        }


class DevicePipeline:
    """Process-wide staging/completion pipe (singleton: :data:`PIPELINE`).

    Hot-path contract: a commit with no device batches costs one WeakSet
    truthiness test (identical to the sync path) — the lock, the worker
    thread, and the metrics handles are only touched by device commits.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        #: FIFO of (commit_time, handles, dispatch_perf) awaiting completion
        self._staged: deque = deque()  # guarded-by: self._cv
        self._active_time: int | None = None  # guarded-by: self._cv
        self._completed_time = -1  # guarded-by: self._cv
        self._worker: threading.Thread | None = None
        self._stop = False  # guarded-by: self._cv
        self._error: BaseException | None = None  # guarded-by: self._cv
        self._busy_s = 0.0  # guarded-by: self._cv
        self._occ_mark: float | None = None  # guarded-by: self._cv
        self._occupancy = 0.0  # guarded-by: self._cv
        self.controller = AdaptiveBatchController()
        self._g_depth = _metrics.REGISTRY.gauge(
            "pathway_device_queue_depth",
            "device-pipeline commits staged or completing",
        )
        self._g_occ = _metrics.REGISTRY.gauge(
            "pathway_device_occupancy_ratio",
            "EMA share of wall time the device completion stage is busy",
        )
        self._h_latency = _metrics.REGISTRY.histogram(
            "pathway_device_dispatch_complete_seconds",
            "device commit dispatch -> in-order completion latency",
            buckets=DISPATCH_BUCKETS,
        )
        self._c_commits = _metrics.REGISTRY.counter(
            "pathway_device_pipeline_commits_total",
            "device commits retired through the async pipeline",
        )

    # -- lifecycle -----------------------------------------------------------

    def configure(self) -> None:
        """Drain outstanding work and re-read the env knobs — tests and
        benches call this between runs instead of mutating the singleton."""
        self.drain()
        with self._cv:
            self._error = None
            self._completed_time = -1
            self._busy_s = 0.0
            self._occ_mark = None
            self._occupancy = 0.0
            self._g_occ.value = 0.0
        self.controller = AdaptiveBatchController()

    def _ensure_worker(self) -> None:
        w = self._worker
        if w is None or not w.is_alive():
            with self._cv:
                self._stop = False
            self._worker = threading.Thread(
                target=self._run_completions,
                name="pw-device-pipeline",
                daemon=True,
            )
            self._worker.start()

    def stop_worker(self, timeout: float = 5.0) -> None:
        """Reap the completion worker (run teardown).  The worker first
        retires anything still staged, so a clean run loses nothing; a
        raising run must not leave the daemon behind to accumulate
        across runs — ``_ensure_worker`` respawns it on next use."""
        w = self._worker
        if w is None:
            return
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if w.is_alive():
            w.join(timeout=timeout)
        if not w.is_alive():
            self._worker = None

    def _take_error_locked(self) -> BaseException | None:
        err = self._error
        self._error = None
        return err

    def _raise_pending(self) -> None:
        with self._cv:
            err = self._take_error_locked()
        if err is not None:
            raise err

    # -- staging side (scheduler thread) -------------------------------------

    def commit_boundary(self, time: int) -> None:
        """End-of-commit hook, replacing the inline decay barrier.

        Sync mode (``PATHWAY_TPU_ASYNC_DEVICE=0``): decay inline —
        bit-identical to the pre-pipeline engine.  Async mode: start the
        D2H DMA for every live handle, stage the commit on the FIFO
        (blocking only when ``depth`` commits are already in flight),
        and return to the host sweep immediately."""
        from pathway_tpu.engine import device as _device
        from pathway_tpu.engine import device_residency as _dres

        # exchange outputs kept device-resident are consumed within the
        # commit that delivered them; materialize any survivor here so
        # HBM stays bounded by one commit and downstream persistence
        # only ever sees host-resident state (exactly-once discipline)
        _dres.decay_resident_batches()
        handles = _device.stage_device_batches()
        if not handles:
            return
        if not async_enabled():
            for handle in handles:
                handle.decay()
            return
        self._raise_pending()
        t0 = _time.perf_counter()
        for handle in handles:
            handle.prefetch()  # start the DMA; never await it here
        self._ensure_worker()
        blocked = False
        ctx = _tracing.current()
        with self._cv:
            while (
                len(self._staged)
                + (1 if self._active_time is not None else 0)
                >= self.controller.depth
            ):
                blocked = True
                if ctx is not None:
                    bp0 = _time.perf_counter()
                self._cv.wait(timeout=60.0)
                if ctx is not None:
                    # genuine pipeline stall: host blocked on the device
                    # stage — attributed to the queue_wait bucket
                    ctx.span(
                        "device-backpressure",
                        "wait",
                        bp0,
                        _time.perf_counter(),
                        inflight=len(self._staged),
                    )
                err = self._take_error_locked()
                if err is not None:
                    raise err
            self._staged.append((int(time), handles, t0))
            self._g_depth.value = float(
                len(self._staged)
                + (1 if self._active_time is not None else 0)
            )
            self._cv.notify_all()
            staged_depth = len(self._staged)
            occupancy = self._occupancy
        self.controller.observe(
            staged_depth=staged_depth, blocked=blocked, occupancy=occupancy
        )
        if ctx is not None:
            ctx.span(
                "device-dispatch",
                "pipeline",
                t0,
                _time.perf_counter(),
                batches=len(handles),
                inflight=staged_depth,
            )

    # -- completion side (worker thread) -------------------------------------

    def _run_completions(self) -> None:
        while True:
            with self._cv:
                # bounded wait + stop flag: an untimed wait here would
                # strand the daemon at shutdown if the final notify races
                # the run teardown
                while not self._staged:
                    if self._stop:
                        return
                    self._cv.wait(timeout=0.5)
                time_, handles, t_dispatch = self._staged.popleft()
                self._active_time = time_
                self._g_depth.value = float(len(self._staged) + 1)
                self._cv.notify_all()
            t0 = _time.perf_counter()
            err: BaseException | None = None
            try:
                for handle in handles:
                    handle.decay()
            except BaseException as e:  # noqa: BLE001 — surfaced on main thread
                err = e
            t1 = _time.perf_counter()
            with self._cv:
                self._busy_s += t1 - t0
                mark = self._occ_mark
                self._occ_mark = t1
                if mark is not None and t1 > mark:
                    ratio = min(1.0, (t1 - t0) / (t1 - mark))
                    self._occupancy = (
                        0.8 * self._occupancy + 0.2 * ratio
                    )
                    self._g_occ.value = round(self._occupancy, 4)
                self._completed_time = time_
                self._active_time = None
                self._g_depth.value = float(len(self._staged))
                self._h_latency.observe(max(0.0, t1 - t_dispatch))
                self._c_commits.inc()
                if err is not None and self._error is None:
                    self._error = err
                self._cv.notify_all()

    # -- barriers (runner thread) --------------------------------------------

    def drain_until(self, time: int) -> None:
        """Block until every staged commit at or before ``time`` has
        completed — THE exactly-once seam: the runner calls this before
        persistence/snapshot ``on_commit`` hooks so a checkpoint for
        commit N is only cut once N's device effects are host-resident."""
        from pathway_tpu.engine import device_residency as _dres

        _dres.decay_resident_batches()
        if self._worker is None:
            return
        with self._cv:
            while (self._staged and self._staged[0][0] <= time) or (
                self._active_time is not None and self._active_time <= time
            ):
                self._cv.wait(timeout=60.0)
        self._raise_pending()

    def drain(self) -> None:
        """Complete everything in flight (run end, pre-snapshot, tests)."""
        from pathway_tpu.engine import device_residency as _dres

        _dres.decay_resident_batches()
        if self._worker is None:
            return
        with self._cv:
            while self._staged or self._active_time is not None:
                self._cv.wait(timeout=60.0)
        self._raise_pending()

    def reset(self) -> None:
        """Recovery path: the in-flight commits belong to a timeline a
        snapshot rollback un-happens.  Completing them is still correct
        (decay only frees HBM and fills host twins) — so drain, then
        drop any queued error: the rolled-back timeline re-derives."""
        try:
            self.drain()
        except BaseException:  # noqa: BLE001 — rolled-back work may not raise
            pass
        with self._cv:
            self._error = None
            self._completed_time = -1

    # -- read side -----------------------------------------------------------

    def inflight(self) -> int:
        with self._cv:
            return len(self._staged) + (
                1 if self._active_time is not None else 0
            )

    def completed_time(self) -> int:
        return self._completed_time

    def occupancy(self) -> float:
        return self._occupancy

    def stats(self) -> dict:
        """Structured roll-up for bench JSON."""
        from pathway_tpu.engine import collective_exchange as _collective
        from pathway_tpu.engine import device_ops as _dops
        from pathway_tpu.engine import device_residency as _dres

        return {
            "enabled": async_enabled(),
            "inflight": self.inflight(),
            "completed_commits": int(self._c_commits.value),
            "occupancy_ratio": round(self._occupancy, 4),
            "dispatch_complete_p50_ms": round(
                self._h_latency.quantile(0.5) * 1000.0, 3
            ),
            "dispatch_complete_p99_ms": round(
                self._h_latency.quantile(0.99) * 1000.0, 3
            ),
            "controller": self.controller.stats(),
            # the device-resident operator kernels share the pipe's
            # device: their launch volume belongs in the same roll-up
            "device_ops": {
                "enabled": _dops.enabled(),
                "hit_counts": _dops.hit_counts(),
            },
            # the collective exchange dispatches through the same device
            # (its all-to-all launches overlap host work the way staged
            # commits do) — surface its engagement next to the pipe's
            "collective_exchange": {
                "enabled": _collective.enabled(),
                "events": dict(_collective.COLLECTIVE_STATS),
            },
            # the residency plane keeps exchange outputs on that same
            # device between operators — its transfer ledger belongs
            # beside the planes that produce and consume the buffers
            "device_residency": _dres.stats(),
        }


#: the process-wide pipeline every scheduler's commit boundary feeds
PIPELINE = DevicePipeline()


def commit_boundary(time: int) -> None:
    PIPELINE.commit_boundary(time)


def drain() -> None:
    PIPELINE.drain()


def drain_until(time: int) -> None:
    PIPELINE.drain_until(time)


def stop_worker() -> None:
    PIPELINE.stop_worker()


def reset() -> None:
    PIPELINE.reset()


def suggested_batch_size() -> int | None:
    """The adaptive controller's current device micro-batch suggestion;
    None in sync mode (executors then use their configured cap).  A
    ``BatchExecutor`` sizer only ever narrows the configured
    ``max_batch_size`` with this value, never exceeds it."""
    if not async_enabled():
        return None
    return PIPELINE.controller.batch_size


def ingest_window_scale() -> float:
    """Multiplier for connector autocommit windows (1.0 when the
    pipeline is off or idle).  Only a congested device stage widens the
    window — host-only programs never see a changed commit cadence."""
    if not async_enabled() or PIPELINE.inflight() == 0:
        return 1.0
    return PIPELINE.controller.window_scale
