"""External index operator: as-of-now retrieval against device-resident state.

Engine-side equivalent of the reference's `UseExternalIndexAsOfNow` timely
operator (reference: src/engine/dataflow/operators/external_index.rs:38 and
the `ExternalIndex` trait src/external_integration/mod.rs:40): the index is
mutable operator state *outside* the incremental collections; queries are
answered against the index state at arrival time and answers are never
revised when the index later changes — only query-row deletions retract
their answers (Appendix B of SURVEY.md).

The TPU implementation keeps the index in HBM (ops/knn.py): adds/removes are
bucket-padded scatter batches, searches are bucket-padded masked matmul +
top-k. Host state is only the key<->slot mapping.
"""

from __future__ import annotations

import time as _time
from typing import Any, NamedTuple, Protocol, Sequence

import numpy as np

from pathway_tpu.engine import device_ops as _dops
from pathway_tpu.engine.batch import DeltaBatch
from pathway_tpu.engine.graph import Node, Scope
from pathway_tpu.engine.value import Pointer, is_error
from pathway_tpu.internals import metrics as _metrics
from pathway_tpu.internals import tracing as _tracing

#: device dispatch volume on the KNN path — how many index mutations and
#: query probes each commit pushes through the pipeline
_KNN_UPDATES = _metrics.REGISTRY.counter(
    "pathway_device_knn_updates_total",
    "key add/remove mutations dispatched to the device KNN index",
)
_KNN_QUERIES = _metrics.REGISTRY.counter(
    "pathway_device_knn_queries_total",
    "query vectors dispatched to the device KNN search",
)


class ExternalIndex(Protocol):
    """Host-facing index contract (add/remove by key, batched search)."""

    def add(self, keys: Sequence[Pointer], vectors: Sequence[Any]) -> None: ...

    def remove(self, keys: Sequence[Pointer]) -> None: ...

    def search(
        self, queries: Sequence[Any], k: int
    ) -> list[list[tuple[Pointer, float]]]: ...


def _bucket(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


_gather_pad_jit = None
_pack_results_jit = None


def _pack_results(scores, slots):
    """Stack (scores f32, slots i32) into ONE int32 array [2, q, k] (scores
    bitcast) so the host pays a single device→host round trip per search —
    each separate small fetch costs a full tunnel RTT on remote devices."""
    global _pack_results_jit
    if _pack_results_jit is None:
        import jax

        @jax.jit
        def pack(s, i):
            import jax.numpy as jnp
            from jax import lax

            return jnp.stack(
                [
                    lax.bitcast_convert_type(
                        s.astype(jnp.float32), jnp.int32
                    ),
                    i.astype(jnp.int32),
                ]
            )

        _pack_results_jit = pack
    return _pack_results_jit(scores, slots)


def _gather_pad(dev, idx_pad, enabled):
    """Bucketed device gather: [B, dim] batch + padded indices -> [b, dim]
    float32 rows, zeroed where disabled. One module-level jit — jax caches
    the compilation per input shape, and all shapes here are bucketed."""
    global _gather_pad_jit
    if _gather_pad_jit is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def gather(d, i, e):
            rows = jnp.take(d, i, axis=0).astype(jnp.float32)
            return jnp.where(e[:, None], rows, 0.0)

        _gather_pad_jit = gather
    return _gather_pad_jit(dev, idx_pad, enabled)


class DeviceKnnIndex:
    """HBM-resident brute-force KNN with a host slot allocator.

    Replaces the reference's CPU brute-force/usearch indexes with the
    fixed-capacity masked slot array of ops/knn.py. Capacity doubles by
    device-side copy when the free list runs dry; update and query batches
    are padded to power-of-two buckets so jit caches stay small.
    """

    def __init__(
        self,
        dim: int,
        metric: str = "cos",
        capacity: int = 1024,
        dtype: Any = None,
        mesh: Any = None,
    ) -> None:
        import jax.numpy as jnp

        from pathway_tpu.ops import knn_init

        self.dim = dim
        self.metric = metric
        self.capacity = capacity
        self.dtype = dtype if dtype is not None else jnp.float32
        self.mesh = mesh
        self.state = knn_init(capacity, dim, self.dtype, mesh=mesh)
        self.key_to_slot: dict[Pointer, int] = {}
        self.slot_to_key: dict[int, Pointer] = {}
        self._free: list[int] = list(range(capacity - 1, -1, -1))

    def __len__(self) -> int:
        return len(self.key_to_slot)

    # -- mutation ------------------------------------------------------------

    def _grow(self) -> None:
        import jax.numpy as jnp

        from pathway_tpu.ops import knn_init
        from pathway_tpu.ops.knn import DeviceKnnState

        old = self.state
        new_capacity = self.capacity * 2
        fresh = knn_init(new_capacity, self.dim, self.dtype, mesh=self.mesh)
        self.state = DeviceKnnState(
            vectors=fresh.vectors.at[: self.capacity].set(old.vectors),
            valid=fresh.valid.at[: self.capacity].set(old.valid),
            norms=fresh.norms.at[: self.capacity].set(old.norms),
        )
        self._free = list(range(new_capacity - 1, self.capacity - 1, -1)) + self._free
        self.capacity = new_capacity

    def _apply(
        self, slots: list[int], vecs: np.ndarray, set_valid: list[bool]
    ) -> None:
        import jax.numpy as jnp

        from pathway_tpu.engine import device_residency as _dres
        from pathway_tpu.ops import knn_update

        n = len(slots)
        if n == 0:
            return
        t0 = _time.perf_counter_ns()
        b = _bucket(n)
        slots_arr = np.full((b,), 0, np.int32)
        slots_arr[:n] = slots
        vec_arr = np.zeros((b, self.dim), np.float32)
        vec_arr[:n] = vecs
        valid_arr = np.zeros((b,), bool)
        valid_arr[:n] = set_valid
        enabled = np.zeros((b,), bool)
        enabled[:n] = True
        _dres.record_h2d(
            slots_arr.nbytes + vec_arr.nbytes + valid_arr.nbytes
            + enabled.nbytes
        )
        self.state = knn_update(
            self.state,
            jnp.asarray(slots_arr),
            jnp.asarray(vec_arr),
            jnp.asarray(valid_arr),
            jnp.asarray(enabled),
        )
        _dops.record_kernel(
            "knn_update", _time.perf_counter_ns() - t0, hits=n
        )

    def add(self, keys: Sequence[Pointer], vectors: Sequence[Any]) -> None:
        from pathway_tpu.engine.device import LazyDeviceVector

        # Group lazy rows by their parent device batch — NOT by contiguous
        # runs: upstream operators iterate key sets and scramble row order,
        # which would fragment a 1000-row commit into ~1000 one-row device
        # updates (measured: 732 updates/commit, whose device-queue depth
        # then stalled the next query's search by ~6 s). One gather+scatter
        # per parent keeps the device queue a few ops deep.
        groups: dict[int, tuple[Any, list[int], list[Pointer]]] = {}
        host_keys: list[Pointer] = []
        host_vecs: list[Any] = []
        for key, vec in zip(keys, vectors):
            if (
                isinstance(vec, LazyDeviceVector)
                and vec.batch.dev is not None
                and tuple(vec.batch.dev.shape[1:]) == (self.dim,)
            ):
                handle, indices, gkeys = groups.setdefault(
                    id(vec.batch), (vec.batch, [], [])
                )
                indices.append(vec.index)
                gkeys.append(key)
            else:
                host_keys.append(key)
                host_vecs.append(vec)
        for handle, indices, gkeys in groups.values():
            if not self._add_device_run(gkeys, handle.dev, indices):
                # replacements take the general path; the lazy rows
                # materialise through their (prefetched) host twin
                self._add_host(
                    gkeys,
                    [LazyDeviceVector(handle, i) for i in indices],
                )
        if host_keys:
            self._add_host(host_keys, host_vecs)

    def _add_host(
        self, keys: Sequence[Pointer], vectors: Sequence[Any]
    ) -> None:
        slots, vecs, valid = [], [], []
        deferred_free: list[int] = []  # freed only after the batch lands, so
        # a replaced key's old slot can't be reused (= written twice) in it
        for key, vec in zip(keys, vectors):
            if key in self.key_to_slot:
                old_slot = self.key_to_slot.pop(key)
                self.slot_to_key.pop(old_slot, None)
                slots.append(old_slot)
                vecs.append(np.zeros((self.dim,), np.float32))
                valid.append(False)
                deferred_free.append(old_slot)
            if not self._free:
                self._apply(slots, np.asarray(vecs, np.float32), valid)
                self._free.extend(deferred_free)
                slots, vecs, valid, deferred_free = [], [], [], []
                if not self._free:
                    self._grow()
            slot = self._free.pop()
            self.key_to_slot[key] = slot
            self.slot_to_key[slot] = key
            slots.append(slot)
            vecs.append(np.asarray(vec, np.float32).reshape(self.dim))
            valid.append(True)
        self._apply(slots, np.asarray(vecs, np.float32), valid)
        self._free.extend(deferred_free)

    def _add_device_run(
        self, keys: Sequence[Pointer], dev: Any, indices: Sequence[int]
    ) -> bool:
        """Transfer-free ingest of one run of lazy rows sharing a live
        device batch (the embedder's jit output): gather on device and
        scatter straight into HBM — no device→host→device round trip
        (the bench pipeline's hot path)."""
        if tuple(dev.shape[1:]) != (self.dim,):
            return False  # rejection must precede any capacity growth
        if any(key in self.key_to_slot for key in keys):
            return False  # replacements take the general path
        while len(self._free) < len(keys):
            self._grow()  # device-side copy; cheaper than a host detour

        import jax.numpy as jnp

        from pathway_tpu.engine import device_residency as _dres
        from pathway_tpu.ops import knn_update

        n = len(keys)
        slots = []
        for key in keys:
            slot = self._free.pop()
            self.key_to_slot[key] = slot
            self.slot_to_key[slot] = key
            slots.append(slot)
        # every device-side shape is bucketed — otherwise each distinct
        # batch length would trigger a fresh compile (deadly over a
        # remote-device link)
        b = _bucket(n)
        slots_arr = np.zeros((b,), np.int32)
        slots_arr[:n] = slots
        enabled = np.zeros((b,), bool)
        enabled[:n] = True
        idx_pad = np.zeros((b,), np.int32)
        idx_pad[:n] = indices
        t0 = _time.perf_counter_ns()
        # only the control arrays go up — the vectors are already resident
        _dres.record_h2d(
            slots_arr.nbytes + enabled.nbytes + idx_pad.nbytes
        )
        enabled_dev = jnp.asarray(enabled)
        gathered = _gather_pad(
            dev, jnp.asarray(idx_pad), enabled_dev
        )
        self.state = knn_update(
            self.state,
            jnp.asarray(slots_arr),
            gathered,
            enabled_dev,
            enabled_dev,
        )
        _dops.record_kernel(
            "knn_update", _time.perf_counter_ns() - t0, hits=n
        )
        return True

    def remove(self, keys: Sequence[Pointer]) -> None:
        slots, vecs, valid = [], [], []
        for key in keys:
            slot = self.key_to_slot.pop(key, None)
            if slot is None:
                continue
            self.slot_to_key.pop(slot, None)
            self._free.append(slot)
            slots.append(slot)
            vecs.append(np.zeros((self.dim,), np.float32))
            valid.append(False)
        self._apply(slots, np.asarray(vecs, np.float32), valid)

    # -- operator persistence -------------------------------------------------

    def op_state(self) -> dict:
        """Device arrays come back as numpy so snapshots pickle (the HBM
        copy is rebuilt on restore)."""
        return {
            "vectors": np.asarray(self.state.vectors),
            "valid": np.asarray(self.state.valid),
            "norms": np.asarray(self.state.norms),
            "key_to_slot": dict(self.key_to_slot),
            "free": list(self._free),
            "capacity": self.capacity,
        }

    def restore_op_state(self, state: dict) -> None:
        import jax.numpy as jnp

        from pathway_tpu.engine import device_residency as _dres
        from pathway_tpu.ops.knn import DeviceKnnState

        self.capacity = state["capacity"]
        self.state = DeviceKnnState(
            vectors=jnp.asarray(state["vectors"]),
            valid=jnp.asarray(state["valid"]),
            norms=jnp.asarray(state["norms"]),
        )
        _dres.record_h2d(
            int(self.state.vectors.nbytes)
            + int(self.state.valid.nbytes)
            + int(self.state.norms.nbytes)
        )
        self.key_to_slot = dict(state["key_to_slot"])
        self.slot_to_key = {s: k for k, s in self.key_to_slot.items()}
        self._free = list(state["free"])

    # -- read snapshots ------------------------------------------------------

    def read_view(self) -> "DeviceKnnIndex":
        """Immutable search-only twin at the current state, for the
        serving plane's per-commit snapshots.

        ``knn_update`` DONATES its input buffers (the scatter reuses
        them), so the view cannot alias ``self.state`` — it takes a
        device-side copy (HBM->HBM, no host transfer).  The slot maps
        are host dicts and copy shallowly.  The view's ``search`` is the
        exact production path, so snapshot reads are bit-identical to a
        synchronous read at the same commit."""
        import jax.numpy as jnp

        view = object.__new__(type(self))
        view.dim = self.dim
        view.metric = self.metric
        view.capacity = self.capacity
        view.dtype = self.dtype
        view.mesh = self.mesh
        view.state = type(self.state)(
            jnp.copy(self.state.vectors),
            jnp.copy(self.state.valid),
            jnp.copy(self.state.norms),
        )
        view.key_to_slot = dict(self.key_to_slot)
        view.slot_to_key = dict(self.slot_to_key)
        view._free = []
        return view

    # -- search --------------------------------------------------------------

    def search(
        self, queries: Sequence[Any], k: int
    ) -> list[list[tuple[Pointer, float]]]:
        import jax.numpy as jnp

        from pathway_tpu.engine import device_residency as _dres
        from pathway_tpu.ops import knn_search
        from pathway_tpu.ops.knn import knn_search_sharded

        n = len(queries)
        if n == 0:
            return []
        k_eff = min(k, self.capacity)
        b = _bucket(n)
        q_dev = None
        from pathway_tpu.engine.device import device_runs

        runs = device_runs(list(queries))
        if (
            len(runs) == 1
            and runs[0][2] is not None
            and tuple(runs[0][2].shape[1:]) == (self.dim,)
        ):
            # query vectors still live on device (embedder output): gather
            # there and fetch only the top-k — one small round trip total
            dev, indices = runs[0][2], runs[0][3]
            idx_pad = np.zeros((b,), np.int32)
            idx_pad[:n] = indices
            enabled = np.zeros((b,), bool)
            enabled[:n] = True
            q_dev = _gather_pad(dev, jnp.asarray(idx_pad), jnp.asarray(enabled))
        if q_dev is None:
            q = np.zeros((b, self.dim), np.float32)
            for i, vec in enumerate(queries):
                q[i] = np.asarray(vec, np.float32).reshape(self.dim)
            _dres.record_h2d(q.nbytes)
            q_dev = jnp.asarray(q)
        t0 = _time.perf_counter_ns()
        if self.mesh is not None:
            scores, slots = knn_search_sharded(
                self.state, q_dev, k_eff, self.mesh, self.metric
            )
        else:
            scores, slots = knn_search(
                self.state, q_dev, k_eff, self.metric
            )
        packed = np.asarray(_pack_results(scores, slots))
        _dres.record_d2h(packed.nbytes)
        _dops.record_kernel(
            "knn_search", _time.perf_counter_ns() - t0, hits=n
        )
        scores = packed[0].view(np.float32)[:n]
        slots = packed[1][:n]
        out: list[list[tuple[Pointer, float]]] = []
        for i in range(n):
            hits = []
            for score, slot in zip(scores[i], slots[i]):
                key = self.slot_to_key.get(int(slot))
                if key is not None and np.isfinite(score):
                    hits.append((key, float(score)))
            out.append(hits)
        return out


class _HostKnnState(NamedTuple):
    """NumPy twin of ops.knn.DeviceKnnState (same field contract)."""

    vectors: np.ndarray  # [capacity, dim]
    valid: np.ndarray  # [capacity] bool
    norms: np.ndarray  # [capacity] float32 — squared L2 norms


class HostKnnIndex(DeviceKnnIndex):
    """CPU/NumPy twin of :class:`DeviceKnnIndex` — the bit-exact host spec
    for the device KNN kernels (PR-2 parity discipline), and the
    accelerator-free engine behind the streaming-RAG host-fallback bench
    leg.

    It *inherits* the slot allocator, bucket padding, replacement and
    growth logic (the behaviors that decide slot ids and therefore tie
    order), overriding only the device seams: state lives in NumPy
    arrays, the scatter update and the masked matmul + top-k run on
    host.  Tie-breaking matches ``lax.top_k`` (lowest slot first) via a
    stable descending argsort.  Float reduction order is the one seam a
    host spec cannot pin per-platform; the parity corpus uses exactly
    representable values so any order sums identically, and the
    check.py parity gate validates the real device per platform.
    """

    def __init__(
        self,
        dim: int,
        metric: str = "cos",
        capacity: int = 1024,
        dtype: Any = None,
        mesh: Any = None,
    ) -> None:
        self.dim = dim
        self.metric = metric
        self.capacity = capacity
        self.dtype = np.float32
        self.mesh = None  # host search never shards
        self.state = _HostKnnState(
            vectors=np.zeros((capacity, dim), np.float32),
            valid=np.zeros((capacity,), bool),
            norms=np.zeros((capacity,), np.float32),
        )
        self.key_to_slot = {}
        self.slot_to_key = {}
        self._free = list(range(capacity - 1, -1, -1))
        self._cow_shared = False

    def _grow(self) -> None:
        old = self.state
        new_capacity = self.capacity * 2
        vectors = np.zeros((new_capacity, self.dim), np.float32)
        valid = np.zeros((new_capacity,), bool)
        norms = np.zeros((new_capacity,), np.float32)
        vectors[: self.capacity] = old.vectors
        valid[: self.capacity] = old.valid
        norms[: self.capacity] = old.norms
        self.state = _HostKnnState(vectors, valid, norms)
        self._cow_shared = False  # growth allocated fresh arrays
        self._free = (
            list(range(new_capacity - 1, self.capacity - 1, -1)) + self._free
        )
        self.capacity = new_capacity

    def _add_device_run(
        self, keys: Sequence[Pointer], dev: Any, indices: Sequence[int]
    ) -> bool:
        # lazy device rows materialise through their (prefetched) host
        # twin on the general path — a host index never touches HBM
        return False

    def _apply(
        self, slots: list[int], vecs: np.ndarray, set_valid: list[bool]
    ) -> None:
        n = len(slots)
        if n == 0:
            return
        if self._cow_shared:
            # a read view shares these arrays: clone before the in-place
            # scatter so the published snapshot stays frozen (the device
            # twin gets this for free — knn_update is functional)
            self.state = _HostKnnState(
                self.state.vectors.copy(),
                self.state.valid.copy(),
                self.state.norms.copy(),
            )
            self._cow_shared = False
        vecs = np.asarray(vecs, np.float32).reshape(n, self.dim)
        idx = np.asarray(slots, np.int64)
        self.state.vectors[idx] = vecs
        self.state.valid[idx] = np.asarray(set_valid, bool)
        # same formula as ops.knn.knn_update: f32 square-sum of the row
        self.state.norms[idx] = np.sum(vecs * vecs, axis=-1)

    def op_state(self) -> dict:
        # explicit copies: the host arrays mutate in place, and a snapshot
        # must not alias live state (the device version copies via jax→np)
        return {
            "vectors": self.state.vectors.copy(),
            "valid": self.state.valid.copy(),
            "norms": self.state.norms.copy(),
            "key_to_slot": dict(self.key_to_slot),
            "free": list(self._free),
            "capacity": self.capacity,
        }

    def restore_op_state(self, state: dict) -> None:
        self.capacity = state["capacity"]
        self.state = _HostKnnState(
            vectors=np.asarray(state["vectors"], np.float32),
            valid=np.asarray(state["valid"], bool),
            norms=np.asarray(state["norms"], np.float32),
        )
        self.key_to_slot = dict(state["key_to_slot"])
        self.slot_to_key = {s: k for k, s in self.key_to_slot.items()}
        self._free = list(state["free"])
        self._cow_shared = False

    def read_view(self) -> "HostKnnIndex":
        """Copy-on-write read view: the view SHARES the live arrays and
        both sides are flagged, so the next in-place scatter on either
        clones first (``_apply``) — publishing an idle index costs two
        dict copies, not an array copy."""
        view = object.__new__(type(self))
        view.dim = self.dim
        view.metric = self.metric
        view.capacity = self.capacity
        view.dtype = self.dtype
        view.mesh = self.mesh
        view.state = self.state
        view.key_to_slot = dict(self.key_to_slot)
        view.slot_to_key = dict(self.slot_to_key)
        view._free = []
        view._cow_shared = True
        self._cow_shared = True
        return view

    def search(
        self, queries: Sequence[Any], k: int
    ) -> list[list[tuple[Pointer, float]]]:
        n = len(queries)
        if n == 0:
            return []
        k_eff = min(k, self.capacity)
        q = np.zeros((n, self.dim), np.float32)
        for i, vec in enumerate(queries):
            q[i] = np.asarray(vec, np.float32).reshape(self.dim)
        db = self.state.vectors
        dots = q @ db.T  # f32 matmul — ops.knn uses Precision.HIGHEST
        if self.metric == "dot":
            scores = dots
        elif self.metric == "cos":
            qn = np.sqrt(np.sum(q * q, axis=-1, keepdims=True))
            dbn = np.sqrt(self.state.norms)[None, :]
            scores = dots / np.maximum(qn * dbn, np.float32(1e-30))
        elif self.metric == "l2sq":
            qn = np.sum(q * q, axis=-1, keepdims=True)
            scores = -(qn + self.state.norms[None, :] - 2.0 * dots)
        else:
            raise ValueError(f"unknown metric {self.metric!r}")
        scores = np.where(self.state.valid[None, :], scores, -np.inf)
        # lax.top_k tie contract: highest score first, lowest slot among
        # equals — a stable argsort on the negated scores reproduces it
        order = np.argsort(-scores, axis=1, kind="stable")[:, :k_eff]
        top = np.take_along_axis(scores, order, axis=1)
        out: list[list[tuple[Pointer, float]]] = []
        for i in range(n):
            hits = []
            for score, slot in zip(top[i], order[i]):
                key = self.slot_to_key.get(int(slot))
                if key is not None and np.isfinite(score):
                    hits.append((key, float(score)))
            out.append(hits)
        return out


class ExternalIndexNode(Node):
    """As-of-now index operator: port 0 = indexed data, port 1 = queries.

    Output: keyed by query id, row = (result_ids: tuple[Pointer],
    result_scores: tuple[float]). Index-side updates within a commit are
    applied before queries of the same commit are answered. Answers stick
    until their query row is deleted.
    """

    def __init__(
        self,
        scope: Scope,
        index_table: Node,
        query_table: Node,
        index: ExternalIndex,
        index_col: int,
        query_col: int,
        k: int,
        limit_col: int | None = None,
    ) -> None:
        super().__init__(scope, [index_table, query_table], 2)
        # NOT ``self.index`` — that is the node's scope position
        # (Node.index), which every scheduler uses to address replicas;
        # shadowing it breaks sharded delivery for index pipelines
        self.ext_index = index
        self.index_col = index_col
        self.query_col = query_col
        self.k = k
        self.limit_col = limit_col

    def op_state(self) -> dict:
        state = super().op_state()
        index_state = getattr(self.ext_index, "op_state", None)
        if index_state is None:
            # silently skipping would resume with an empty index while the
            # reader has already seeked past the rows that populated it
            raise TypeError(
                f"{type(self.ext_index).__name__} does not implement "
                "op_state/restore_op_state, so it cannot be used with "
                "PersistenceMode.OPERATOR_PERSISTING"
            )
        state["index"] = index_state()
        return state

    def restore_op_state(self, state: dict) -> None:
        super().restore_op_state(state)
        if "index" in state and hasattr(self.ext_index, "restore_op_state"):
            self.ext_index.restore_op_state(state["index"])

    def process(self, time: int) -> DeltaBatch:
        index_batch = self.take(0)
        query_batch = self.take(1)

        # 1. fold index-side deltas into device state
        add_keys: list[Pointer] = []
        add_vecs: list[Any] = []
        rm_keys: list[Pointer] = []
        for key, row, diff in index_batch:
            vec = row[self.index_col]
            if diff > 0:
                if is_error(vec) or vec is None:
                    self.report(key, "error/None vector in index input")
                    continue
                add_keys.append(key)
                add_vecs.append(vec)
            else:
                rm_keys.append(key)
        # removes first so a same-commit delete+insert of a key nets to add
        if rm_keys or add_keys:
            import time as _t

            t0 = _t.perf_counter()
            if rm_keys:
                add_set = set(add_keys)
                self.ext_index.remove(
                    [k_ for k_ in rm_keys if k_ not in add_set]
                )
            if add_keys:
                self.ext_index.add(add_keys, add_vecs)
            _KNN_UPDATES.inc(len(rm_keys) + len(add_keys))
            ctx = _tracing.current()
            if ctx is not None:
                ctx.span(
                    "knn-update",
                    "pipeline",
                    t0,
                    _t.perf_counter(),
                    adds=len(add_keys),
                    removes=len(rm_keys),
                )

        # 2. answer new queries as-of-now; retract answers of deleted queries
        out = DeltaBatch()
        pending: list[tuple[Pointer, Any, int]] = []
        retracted: set[Pointer] = set()
        for key, row, diff in query_batch:
            if diff < 0:
                prev = self.current.get(key)
                if prev is not None and key not in retracted:
                    out.append(key, prev, -1)
                    retracted.add(key)
                continue
            vec = row[self.query_col]
            if is_error(vec) or vec is None:
                self.report(key, "error/None vector in query input")
                continue
            limit = self.k
            if self.limit_col is not None:
                lv = row[self.limit_col]
                if lv is not None and not is_error(lv):
                    limit = int(lv)
            pending.append((key, vec, limit))
        if pending:
            import time as _t

            max_k = max(limit for _k, _v, limit in pending)
            t0 = _t.perf_counter()
            results = self.ext_index.search([v for _k, v, _l in pending], max_k)
            _KNN_QUERIES.inc(len(pending))
            ctx = _tracing.current()
            if ctx is not None:
                ctx.span(
                    "knn-search",
                    "pipeline",
                    t0,
                    _t.perf_counter(),
                    queries=len(pending),
                    k=max_k,
                )
            for (key, _vec, limit), hits in zip(pending, results):
                hits = hits[:limit]
                # re-query of a live key replaces its previous answer (unless
                # the deletion pass of this commit already retracted it)
                prev = self.current.get(key)
                if prev is not None and key not in retracted:
                    out.append(key, prev, -1)
                out.append(
                    key,
                    (
                        tuple(hk for hk, _s in hits),
                        tuple(s for _hk, s in hits),
                    ),
                    1,
                )
        return out.consolidate()
