"""Engine reducers with retraction support.

New implementation of the reference reducer set
(reference: src/engine/reduce.rs:22-38 — Count, IntSum/FloatSum/ArraySum,
Unique, Min/ArgMin/Max/ArgMax, SortedTuple, Tuple, Any, Stateful, Earliest,
Latest). Each reducer keeps per-group state that supports both insertions and
retractions (diff < 0): semigroup reducers (count/sum) keep a running value,
the rest keep a counted multiset and recompute on demand.
"""

from __future__ import annotations

import enum
from typing import Any, Callable

import numpy as np

from pathway_tpu.engine.value import ERROR, is_error


class ReducerKind(enum.Enum):
    COUNT = "count"
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    ARG_MIN = "arg_min"
    ARG_MAX = "arg_max"
    UNIQUE = "unique"
    ANY = "any"
    SORTED_TUPLE = "sorted_tuple"
    TUPLE = "tuple"
    NDARRAY = "ndarray"
    EARLIEST = "earliest"
    LATEST = "latest"
    STATEFUL = "stateful"
    COUNT_DISTINCT = "count_distinct"


def _token(value: Any) -> Any:
    """Hashable token for multiset bookkeeping (ndarrays are unhashable)."""
    if isinstance(value, np.ndarray):
        return ("__nd__", str(value.dtype), value.shape, value.tobytes())
    if isinstance(value, (list, dict)):
        return ("__repr__", repr(value))
    try:
        hash(value)
    except TypeError:
        return ("__repr__", repr(value))
    return value


class ReducerState:
    """Base: counted multiset of argument tuples with (time, seq) order info."""

    __slots__ = ("counts", "values", "order", "total", "seq")

    def __init__(self) -> None:
        self.counts: dict[Any, int] = {}
        self.values: dict[Any, Any] = {}  # token -> actual args tuple
        self.order: dict[Any, tuple[int, int]] = {}  # token -> (time, seq) first seen
        self.total = 0
        self.seq = 0

    def update(self, args: tuple, diff: int, time: int) -> None:
        tok = _token(args)
        cnt = self.counts.get(tok, 0) + diff
        self.total += diff
        if cnt <= 0:
            self.counts.pop(tok, None)
            self.values.pop(tok, None)
            self.order.pop(tok, None)
        else:
            if tok not in self.counts:
                self.order[tok] = (time, self.seq)
                self.seq += 1
                self.values[tok] = args
            self.counts[tok] = cnt

    def is_empty(self) -> bool:
        return self.total <= 0 and not self.counts

    def iter_args(self):
        """Yield (args, count, order) for each distinct entry."""
        for tok, cnt in self.counts.items():
            yield self.values[tok], cnt, self.order[tok]


class Reducer:
    """A reducer over one or more argument columns."""

    kind: ReducerKind
    n_args = 1

    def __init__(self, **options: Any) -> None:
        self.options = options

    def make_state(self) -> Any:
        return ReducerState()

    def update(self, state: Any, args: tuple, diff: int, time: int) -> None:
        state.update(args, diff, time)

    def compute(self, state: Any) -> Any:
        raise NotImplementedError

    def is_empty(self, state: Any) -> bool:
        return state.is_empty()


class _RunningState:
    __slots__ = ("count", "acc", "error_count")

    def __init__(self) -> None:
        self.count = 0
        self.acc: Any = None
        self.error_count = 0


class CountReducer(Reducer):
    kind = ReducerKind.COUNT
    n_args = 0

    def make_state(self) -> _RunningState:
        return _RunningState()

    def update(self, state: _RunningState, args: tuple, diff: int, time: int) -> None:
        state.count += diff

    def compute(self, state: _RunningState) -> Any:
        return state.count

    def is_empty(self, state: _RunningState) -> bool:
        return state.count <= 0


class SumReducer(Reducer):
    """Running-total sum for int/float/ndarray (semigroup with inverse)."""

    kind = ReducerKind.SUM

    def make_state(self) -> _RunningState:
        return _RunningState()

    def update(self, state: _RunningState, args: tuple, diff: int, time: int) -> None:
        (value,) = args
        state.count += diff
        if is_error(value):
            state.error_count += diff
            return
        if value is None:
            return
        contribution = value * diff if not isinstance(value, bool) else int(value) * diff
        if state.acc is None:
            state.acc = contribution
        else:
            state.acc = state.acc + contribution

    def compute(self, state: _RunningState) -> Any:
        if state.error_count > 0:
            return ERROR
        if state.acc is None:
            return 0
        return state.acc

    def is_empty(self, state: _RunningState) -> bool:
        return state.count <= 0


class MinReducer(Reducer):
    kind = ReducerKind.MIN

    def compute(self, state: ReducerState) -> Any:
        best = None
        try:
            for args, _cnt, _ord in state.iter_args():
                v = args[0]
                if is_error(v):
                    return ERROR
                if v is None:
                    continue
                if best is None or v < best:
                    best = v
        except TypeError:
            return ERROR  # incomparable values poison the aggregate
        return best


class MaxReducer(Reducer):
    kind = ReducerKind.MAX

    def compute(self, state: ReducerState) -> Any:
        best = None
        try:
            for args, _cnt, _ord in state.iter_args():
                v = args[0]
                if is_error(v):
                    return ERROR
                if v is None:
                    continue
                if best is None or v > best:
                    best = v
        except TypeError:
            return ERROR
        return best


class ArgMinReducer(Reducer):
    kind = ReducerKind.ARG_MIN
    n_args = 2  # (value, arg)

    def compute(self, state: ReducerState) -> Any:
        best = None
        best_arg = None
        try:
            for args, _cnt, _ord in state.iter_args():
                v, a = args
                if is_error(v) or is_error(a):
                    return ERROR
                if v is None:
                    continue
                if best is None or (v, _token(a)) < best:
                    best = (v, _token(a))
                    best_arg = a
        except TypeError:
            return ERROR
        return best_arg


class ArgMaxReducer(Reducer):
    kind = ReducerKind.ARG_MAX
    n_args = 2

    def compute(self, state: ReducerState) -> Any:
        best = None
        best_arg = None
        try:
            for args, _cnt, _ord in state.iter_args():
                v, a = args
                if is_error(v) or is_error(a):
                    return ERROR
                if v is None:
                    continue
                if best is None or (v, _token(a)) > best:
                    best = (v, _token(a))
                    best_arg = a
        except TypeError:
            return ERROR
        return best_arg


class UniqueReducer(Reducer):
    kind = ReducerKind.UNIQUE

    def compute(self, state: ReducerState) -> Any:
        distinct = [args[0] for args, _cnt, _ord in state.iter_args()]
        non_none = [v for v in distinct if v is not None]
        if len({_token(v) for v in non_none}) > 1:
            return ERROR  # more than one distinct value
        return non_none[0] if non_none else None


class AnyReducer(Reducer):
    """Deterministic 'pick any': smallest by token order."""

    kind = ReducerKind.ANY

    def compute(self, state: ReducerState) -> Any:
        best = None
        best_tok = None
        for args, _cnt, _ord in state.iter_args():
            v = args[0]
            if is_error(v):
                continue
            tok = repr(_token(v))
            if best_tok is None or tok < best_tok:
                best_tok = tok
                best = v
        return best


class SortedTupleReducer(Reducer):
    kind = ReducerKind.SORTED_TUPLE

    def __init__(self, skip_nones: bool = False, **options: Any) -> None:
        super().__init__(**options)
        self.skip_nones = skip_nones

    def compute(self, state: ReducerState) -> Any:
        vals = []
        for args, cnt, _ord in state.iter_args():
            v = args[0]
            if is_error(v):
                return ERROR
            if v is None and self.skip_nones:
                continue
            vals.extend([v] * cnt)
        try:
            return tuple(sorted(vals))
        except TypeError:
            return tuple(sorted(vals, key=lambda v: repr(v)))


class TupleReducer(Reducer):
    """Values ordered by insertion order (time, seq) — stable across runs."""

    kind = ReducerKind.TUPLE

    def __init__(self, skip_nones: bool = False, **options: Any) -> None:
        super().__init__(**options)
        self.skip_nones = skip_nones

    def compute(self, state: ReducerState) -> Any:
        entries = []
        for args, cnt, order in state.iter_args():
            v = args[0]
            if is_error(v):
                return ERROR
            if v is None and self.skip_nones:
                continue
            entries.append((order, v, cnt))
        entries.sort(key=lambda e: e[0])
        out: list[Any] = []
        for _order, v, cnt in entries:
            out.extend([v] * cnt)
        return tuple(out)


class NdarrayReducer(Reducer):
    kind = ReducerKind.NDARRAY

    def compute(self, state: ReducerState) -> Any:
        entries = []
        for args, cnt, order in state.iter_args():
            v = args[0]
            if is_error(v):
                return ERROR
            entries.append((order, v, cnt))
        entries.sort(key=lambda e: e[0])
        out: list[Any] = []
        for _order, v, cnt in entries:
            out.extend([v] * cnt)
        return np.array(out)


class EarliestReducer(Reducer):
    kind = ReducerKind.EARLIEST

    def compute(self, state: ReducerState) -> Any:
        best = None
        best_order = None
        for args, _cnt, order in state.iter_args():
            if best_order is None or order < best_order:
                best_order = order
                best = args[0]
        return best


class LatestReducer(Reducer):
    kind = ReducerKind.LATEST

    def compute(self, state: ReducerState) -> Any:
        best = None
        best_order = None
        for args, _cnt, order in state.iter_args():
            if best_order is None or order > best_order:
                best_order = order
                best = args[0]
        return best


class CountDistinctReducer(Reducer):
    kind = ReducerKind.COUNT_DISTINCT

    def compute(self, state: ReducerState) -> Any:
        return len(state.counts)


class StatefulReducer(Reducer):
    """Custom combine over the full multiset (BaseCustomAccumulator backing).

    ``combine(rows: list[tuple[args, count]]) -> value`` recomputes from the
    retained multiset — correct under retraction for any user logic
    (reference: Stateful{combine_fn} reduce.rs:36 + stateful_reduce.rs:20).
    """

    kind = ReducerKind.STATEFUL

    def __init__(self, combine: Callable[[list[tuple[tuple, int]]], Any], n_args: int = 1, **options: Any) -> None:
        super().__init__(**options)
        self.combine = combine
        self.n_args = n_args

    def compute(self, state: ReducerState) -> Any:
        entries = []
        for args, cnt, order in state.iter_args():
            entries.append((order, args, cnt))
        entries.sort(key=lambda e: e[0])
        try:
            return self.combine([(args, cnt) for _o, args, cnt in entries])
        except Exception:  # noqa: BLE001
            return ERROR


REDUCER_CLASSES: dict[ReducerKind, type[Reducer]] = {
    ReducerKind.COUNT: CountReducer,
    ReducerKind.SUM: SumReducer,
    ReducerKind.MIN: MinReducer,
    ReducerKind.MAX: MaxReducer,
    ReducerKind.ARG_MIN: ArgMinReducer,
    ReducerKind.ARG_MAX: ArgMaxReducer,
    ReducerKind.UNIQUE: UniqueReducer,
    ReducerKind.ANY: AnyReducer,
    ReducerKind.SORTED_TUPLE: SortedTupleReducer,
    ReducerKind.TUPLE: TupleReducer,
    ReducerKind.NDARRAY: NdarrayReducer,
    ReducerKind.EARLIEST: EarliestReducer,
    ReducerKind.LATEST: LatestReducer,
    ReducerKind.STATEFUL: StatefulReducer,
    ReducerKind.COUNT_DISTINCT: CountDistinctReducer,
}


def make_reducer(kind: ReducerKind, **options: Any) -> Reducer:
    return REDUCER_CLASSES[kind](**options)
