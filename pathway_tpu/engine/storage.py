"""Database / message-queue / object-store readers and writers.

New implementations of the reference's storage drivers
(src/connectors/data_storage.rs): SqliteReader (:1396 — snapshot-diff
polling keyed by rowid), KafkaReader (:673) behind an injectable transport
(no kafka client in this image; the seam matches what a confluent-kafka
consumer provides), object-store (S3-shaped) scanner (scanner/s3.rs) behind
an injectable client, and writers: Psql (:1061), Elasticsearch (:1317),
MongoDB, Kafka (:1239) — each over an injected connection/client so the
wire protocol lives outside the engine and tests run offline.
"""

from __future__ import annotations

import sqlite3
from typing import Any, Callable, Protocol, Sequence

from pathway_tpu.engine.connectors import (
    DELETE,
    INSERT,
    ParsedEvent,
    Parser,
    Reader,
)
from pathway_tpu.engine.value import Json, Pointer


class TransparentParser(Parser):
    """Reader already produced ParsedEvents; pass them through (reference
    TransparentParser data_format.rs:1553)."""

    def __init__(self, column_names: Sequence[str], session_type: str = "native"):
        super().__init__(column_names)
        self.session_type = session_type

    def parse(self, payload: Any) -> list[ParsedEvent]:
        return list(payload)


# -- SQLite -------------------------------------------------------------------


class SqliteReader(Reader):
    """Poll a SQLite table and emit keyed insert/delete diffs.

    Mirrors the reference SqliteReader (data_storage.rs:1396): watch
    ``PRAGMA data_version`` (cheap change hint across connections), then
    re-scan ``SELECT cols, _rowid_`` and diff against the stored state —
    new rowids insert, changed rows delete+insert, missing rowids delete.
    Events are keyed by rowid so updates revise the same engine row.
    """

    def __init__(
        self,
        path: str,
        table_name: str,
        column_names: Sequence[str],
        mode: str = "streaming",
    ) -> None:
        self.path = path
        self.table_name = table_name
        self.column_names = list(column_names)
        self.mode = mode
        self._conn: sqlite3.Connection | None = None
        self._state: dict[int, tuple] = {}
        self._last_version: int | None = None
        self._done_static = False

    def _connection(self) -> sqlite3.Connection:
        if self._conn is None:
            self._conn = sqlite3.connect(self.path)
        return self._conn

    def _scan(self) -> list[ParsedEvent]:
        conn = self._connection()
        cols = ",".join(self.column_names)
        rows = conn.execute(
            f"SELECT {cols},_rowid_ FROM {self.table_name}"
        ).fetchall()
        events: list[ParsedEvent] = []
        present: set[int] = set()
        for row in rows:
            rowid = row[-1]
            values = tuple(row[:-1])
            present.add(rowid)
            prev = self._state.get(rowid)
            if prev is None:
                events.append(ParsedEvent(INSERT, values, key=(rowid,)))
                self._state[rowid] = values
            elif prev != values:
                events.append(ParsedEvent(DELETE, prev, key=(rowid,)))
                events.append(ParsedEvent(INSERT, values, key=(rowid,)))
                self._state[rowid] = values
        for rowid in list(self._state):
            if rowid not in present:
                events.append(
                    ParsedEvent(DELETE, self._state.pop(rowid), key=(rowid,))
                )
        return events

    def poll(self) -> tuple[list[tuple[Any, str, dict]], bool]:
        if self._done_static:
            return [], True
        conn = self._connection()
        version = conn.execute("PRAGMA data_version").fetchone()[0]
        if self._last_version == version and self._state:
            # data_version only changes on writes from *other* connections
            return [], self.mode == "static"
        self._last_version = version
        events = self._scan()
        if self.mode == "static":
            self._done_static = True
        src = f"sqlite:{self.path}:{self.table_name}"
        entries = [(events, src, {})] if events else []
        return entries, self.mode == "static"

    # persistence hooks (engine/persistence.py PersistentDriver)
    def state(self) -> dict:
        return {
            "rows": {str(k): list(v) for k, v in self._state.items()},
            "done_static": self._done_static,
        }

    def restore_state(self, state: dict) -> None:
        self._state = {
            int(k): tuple(v) for k, v in state.get("rows", {}).items()
        }
        self._done_static = bool(state.get("done_static", False))


# -- Kafka-shaped message transport -------------------------------------------


class Message:
    """One queue record: (key, value) bytes plus source coordinates."""

    __slots__ = ("key", "value", "topic", "partition", "offset")

    def __init__(
        self,
        value: bytes | str | None,
        key: bytes | str | None = None,
        topic: str = "",
        partition: int = 0,
        offset: int = 0,
    ) -> None:
        self.key = key
        self.value = value
        self.topic = topic
        self.partition = partition
        self.offset = offset


class MessageTransport(Protocol):
    """What a Kafka/NATS/Redpanda consumer must provide. A real deployment
    wraps confluent-kafka; tests inject an in-memory transport."""

    def poll_messages(self) -> list[Message]: ...

    def finished(self) -> bool: ...


class InMemoryTransport:
    """In-memory MessageTransport for tests and demos: push messages, then
    optionally close. Thread-safe enough for a single producer thread."""

    def __init__(self, topic: str = "topic") -> None:
        self.topic = topic
        self._messages: list[Message] = []
        self._offset = 0
        self._closed = False

    def produce(self, value: Any, key: Any = None) -> None:
        self._messages.append(
            Message(
                value,
                key=key,
                topic=self.topic,
                partition=0,
                offset=len(self._messages),
            )
        )

    def close(self) -> None:
        self._closed = True

    def poll_messages(self) -> list[Message]:
        out = self._messages[self._offset :]
        self._offset = len(self._messages)
        return out

    def finished(self) -> bool:
        return self._closed and self._offset == len(self._messages)


class MessageQueueReader(Reader):
    """Reader over a MessageTransport; payloads are (key, value) pairs for
    the parser (reference KafkaReader data_storage.rs:673 — per-partition
    offsets tracked for persistence)."""

    def __init__(self, transport: Any) -> None:
        self.transport = transport
        self._offsets: dict[tuple[str, int], int] = {}

    def poll(self) -> tuple[list[tuple[Any, str, dict]], bool]:
        entries = []
        for msg in self.transport.poll_messages():
            coord = (msg.topic, msg.partition)
            seen = self._offsets.get(coord)
            if seen is not None and msg.offset <= seen:
                continue  # already consumed before a resume
            self._offsets[coord] = msg.offset
            entries.append(
                (
                    (msg.key, msg.value),
                    f"{msg.topic}:{msg.partition}",
                    {
                        "topic": msg.topic,
                        "partition": msg.partition,
                        "offset": msg.offset,
                    },
                )
            )
        return entries, self.transport.finished()

    def state(self) -> dict:
        return {
            "offsets": {f"{t}\x00{p}": o for (t, p), o in self._offsets.items()}
        }

    def restore_state(self, state: dict) -> None:
        self._offsets = {}
        for k, o in state.get("offsets", {}).items():
            topic, _, part = k.partition("\x00")
            self._offsets[(topic, int(part))] = int(o)
        seek = getattr(self.transport, "seek", None)
        if seek is not None:
            for (topic, part), o in self._offsets.items():
                seek(topic, part, o + 1)


# -- object store (S3-shaped) --------------------------------------------------


class ObjectStoreClient(Protocol):
    """Minimal S3-shaped client: list object keys under a prefix with a
    version signature, and fetch one. boto3 adapts trivially; tests use
    DictObjectStore."""

    def list_objects(self, prefix: str) -> list[tuple[str, str]]:
        """-> [(key, version-signature e.g. etag)]"""
        ...

    def get_object(self, key: str) -> bytes: ...


class DictObjectStore:
    """In-memory ObjectStoreClient (tests / demos)."""

    def __init__(self) -> None:
        self.objects: dict[str, bytes] = {}
        self._version = 0

    def put_object(self, key: str, data: bytes | str) -> None:
        if isinstance(data, str):
            data = data.encode("utf-8")
        self._version += 1
        self.objects[key] = data

    def delete_object(self, key: str) -> None:
        self.objects.pop(key, None)

    def list_objects(self, prefix: str) -> list[tuple[str, str]]:
        import hashlib

        out = []
        for key in sorted(self.objects):
            if key.startswith(prefix):
                etag = hashlib.md5(self.objects[key]).hexdigest()
                out.append((key, etag))
        return out

    def get_object(self, key: str) -> bytes:
        return self.objects[key]


class ObjectStoreReader(Reader):
    """Scan an object-store prefix like the reference's S3 scanner
    (scanner/s3.rs): new keys insert, changed versions replace, deleted
    keys retract (streaming mode)."""

    replaces_sources = True

    def __init__(
        self, client: Any, prefix: str, mode: str = "streaming", binary: bool = False
    ) -> None:
        self.client = client
        self.prefix = prefix
        self.mode = mode
        self.binary = binary
        self._seen: dict[str, str] = {}
        self._done_static = False

    def _payload(self, key: str) -> Any:
        data = self.client.get_object(key)
        return data if self.binary else data.decode("utf-8", errors="replace")

    def poll(self) -> tuple[list[tuple[Any, str, dict]], bool]:
        if self._done_static:
            return [], True
        entries = []
        current = dict(self.client.list_objects(self.prefix))
        for key, sig in current.items():
            if self._seen.get(key) != sig:
                entries.append(
                    (self._payload(key), key, {"path": key, "deleted": False})
                )
        for key in set(self._seen) - set(current):
            entries.append((None, key, {"path": key, "deleted": True}))
        self._seen = current
        if self.mode == "static":
            self._done_static = True
        return entries, self.mode == "static"

    def state(self) -> dict:
        return {"seen": dict(self._seen), "done_static": self._done_static}

    def restore_state(self, state: dict) -> None:
        self._seen = dict(state.get("seen", {}))
        self._done_static = False


class ObjectStoreWriter:
    """Write one object per commit timestamp under ``prefix`` using a
    line formatter (the shape of the reference's S3 file sink)."""

    def __init__(
        self,
        client: Any,
        prefix: str,
        formatter: Any,
        column_names: Sequence[str],
    ) -> None:
        self.client = client
        self.prefix = prefix.rstrip("/")
        self.formatter = formatter
        self.column_names = list(column_names)
        self._lines: list[str] = []
        self._part = 0

    def on_change(self, key: Pointer, values: tuple, time: int, diff: int) -> None:
        self._lines.append(
            self.formatter.format(key, values, self.column_names, time, diff)
        )

    def on_time_end(self, time: int) -> None:
        if not self._lines:
            return
        name = f"{self.prefix}/part-{self._part:06d}-{time}.jsonl"
        self.client.put_object(name, "\n".join(self._lines) + "\n")
        self._lines = []
        self._part += 1

    def on_end(self) -> None:
        self.on_time_end(-1)


# -- database / service writers ----------------------------------------------


class SqlExecutor(Protocol):
    """One method: run a statement with $1-style params. psycopg2 adapts by
    translating placeholders; tests record or execute against sqlite."""

    def execute(self, statement: str, params: Sequence[Any]) -> None: ...


class PsqlWriter:
    """Postgres sink over an injected SqlExecutor + Psql formatter
    (reference PsqlWriter data_storage.rs:1061: per-time transactional
    batches)."""

    def __init__(self, executor: Any, formatter: Any) -> None:
        self.executor = executor
        self.formatter = formatter

    def on_change(self, key: Pointer, values: tuple, time: int, diff: int) -> None:
        stmt, params = self.formatter.format(key, values, time, diff)
        self.executor.execute(stmt, params)

    def on_time_end(self, time: int) -> None:
        commit = getattr(self.executor, "commit", None)
        if commit is not None:
            commit()

    def on_end(self) -> None:
        self.on_time_end(-1)


class ElasticsearchWriter:
    """Index one document per change (reference ElasticSearchWriter
    data_storage.rs:1317). Client contract: ``index(index, document)``."""

    def __init__(self, client: Any, index_name: str, formatter: Any) -> None:
        self.client = client
        self.index_name = index_name
        self.formatter = formatter

    def on_change(self, key: Pointer, values: tuple, time: int, diff: int) -> None:
        self.client.index(self.index_name, self.formatter.format(key, values, time, diff))

    def on_time_end(self, time: int) -> None:
        # bulk clients buffer per commit (one _bulk request per time)
        flush = getattr(self.client, "flush", None)
        if flush is not None:
            flush()

    def on_end(self) -> None:
        self.on_time_end(-1)


class MongoWriter:
    """Insert documents per change (reference MongoWriter via data_lake
    writer machinery; documents carry time/diff like BsonFormatter).
    Client contract: ``insert_many(collection, [docs])``."""

    def __init__(self, client: Any, collection: str, formatter: Any) -> None:
        self.client = client
        self.collection = collection
        self.formatter = formatter
        self._batch: list[dict] = []

    def on_change(self, key: Pointer, values: tuple, time: int, diff: int) -> None:
        self._batch.append(self.formatter.format(key, values, time, diff))

    def on_time_end(self, time: int) -> None:
        if self._batch:
            self.client.insert_many(self.collection, self._batch)
            self._batch = []

    def on_end(self) -> None:
        self.on_time_end(-1)


class MessageQueueWriter:
    """Produce one message per change onto a transport topic (reference
    KafkaWriter data_storage.rs:1239). Transport contract:
    ``produce(value, key=)``."""

    def __init__(
        self,
        transport: Any,
        formatter: Any,
        column_names: Sequence[str],
        key_index: int | None = None,
    ) -> None:
        self.transport = transport
        self.formatter = formatter
        self.column_names = list(column_names)
        self.key_index = key_index

    def on_change(self, key: Pointer, values: tuple, time: int, diff: int) -> None:
        payload = self.formatter.format(
            key, values, self.column_names, time, diff
        )
        msg_key = None
        if self.key_index is not None:
            msg_key = str(values[self.key_index]).encode()
        self.transport.produce(payload, key=msg_key)

    def on_time_end(self, time: int) -> None:
        flush = getattr(self.transport, "flush", None)
        if flush is not None:
            flush()

    def on_end(self) -> None:
        self.on_time_end(-1)
