"""Engine persistence: backends, input-event journals, resumable drivers.

Reference: src/persistence/ — PersistenceBackend trait (backends/mod.rs:50),
InputSnapshotWriter/Reader event journal (input_snapshot.rs), metadata
threshold protocol (state.rs), connector rewind (connectors/mod.rs:223-341).

The journal for a persistent source is a sequence of pickled *segments*, one
per commit: ``{"events": [(kind, key, row), ...], "reader": state,
"driver": state}``. A crash mid-write leaves a truncated tail segment that
replay detects and discards — so restarts resume from the last complete
commit (the reference's "last finalized time" threshold, state.rs:129-150).
"""

from __future__ import annotations

import io
import os
import pickle
from typing import Any, Iterator

from pathway_tpu.engine.graph import InputSession


class PersistenceBackend:
    """Append/overwrite/read named binary streams
    (reference backends/mod.rs:50)."""

    def append(self, name: str, payload: bytes) -> None:
        raise NotImplementedError

    def write(self, name: str, payload: bytes) -> None:
        """Atomic overwrite."""
        raise NotImplementedError

    def read(self, name: str) -> bytes:
        raise NotImplementedError

    def exists(self, name: str) -> bool:
        raise NotImplementedError


class FileBackend(PersistenceBackend):
    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, name: str) -> str:
        # collision-free escaping: distinct names never share a file
        from urllib.parse import quote

        return os.path.join(self.root, quote(name, safe=""))

    def append(self, name: str, payload: bytes) -> None:
        with open(self._path(name), "ab") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())

    def write(self, name: str, payload: bytes) -> None:
        path = self._path(name)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def read(self, name: str) -> bytes:
        try:
            with open(self._path(name), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return b""

    def exists(self, name: str) -> bool:
        return os.path.exists(self._path(name))


class MemoryBackend(PersistenceBackend):
    def __init__(self) -> None:
        self._data: dict[str, bytes] = {}

    def append(self, name: str, payload: bytes) -> None:
        self._data[name] = self._data.get(name, b"") + payload

    def write(self, name: str, payload: bytes) -> None:
        self._data[name] = payload

    def read(self, name: str) -> bytes:
        return self._data.get(name, b"")

    def exists(self, name: str) -> bool:
        return name in self._data


def _segments(raw: bytes) -> Iterator[dict]:
    """Yield complete pickled segments; stop silently at a truncated tail."""
    buf = io.BytesIO(raw)
    while True:
        try:
            yield pickle.load(buf)
        except EOFError:
            return
        except (pickle.UnpicklingError, AttributeError, ValueError):
            return  # truncated/corrupt tail from a crash mid-append


class RecordingSession:
    """Proxy in front of an InputSession journaling
    (kind, key, row, source_id) — the source attribution lets replay rebuild
    the driver's per-source row map without persisting it per commit."""

    def __init__(self, session: InputSession) -> None:
        self._session = session
        self.buffer: list[tuple[str, Any, Any, str | None]] = []
        self._source: str | None = None

    def on_source(self, source_id: str) -> None:
        self._source = source_id

    def insert(self, key: Any, row: tuple) -> None:
        self.buffer.append(("insert", key, row, self._source))
        self._session.insert(key, row)

    def remove(self, key: Any, row: tuple | None = None) -> None:
        self.buffer.append(("remove", key, row, self._source))
        self._session.remove(key, row)

    def take(self) -> list[tuple[str, Any, Any, str | None]]:
        out = self.buffer
        self.buffer = []
        return out


class PersistentDriver:
    """Wraps an InputDriver with journaling + replay-on-startup.

    ``replay()`` must run before the first poll: it pushes the journaled
    events of every complete commit back into the session and restores the
    reader's and driver's positional state so re-reads don't double-emit.
    """

    def __init__(
        self, driver: Any, backend: PersistenceBackend, persistent_id: str
    ) -> None:
        self.driver = driver
        self.backend = backend
        self.name = f"journal-{persistent_id}"
        self.recorder = RecordingSession(driver.session)
        driver.session = self.recorder
        self.replayed = False

    # -- replay --------------------------------------------------------------

    def replay(self) -> None:
        self.replayed = True
        raw = self.backend.read(self.name)
        reader_state = None
        seq = None
        per_source: dict[str, list] = {}
        for segment in _segments(raw):
            for kind, key, row, source_id in segment["events"]:
                # replay bypasses the recorder: replayed events are already
                # journaled
                if kind == "insert":
                    self.recorder._session.insert(key, row)
                    if source_id is not None:
                        per_source.setdefault(source_id, []).append((key, row))
                else:
                    self.recorder._session.remove(key, row)
                    if source_id is not None and source_id in per_source:
                        per_source[source_id] = [
                            (k, r)
                            for k, r in per_source[source_id]
                            if k != key
                        ]
            reader_state = segment.get("reader", reader_state)
            seq = segment.get("seq", seq)
        if reader_state is not None and hasattr(self.driver.reader, "restore_state"):
            self.driver.reader.restore_state(reader_state)
        if seq is not None:
            self.driver._seq = seq
        if self.driver.reader.replaces_sources:
            self.driver._per_source_rows = {
                s: rows for s, rows in per_source.items() if rows
            }

    # -- driver protocol -----------------------------------------------------

    def poll(self) -> str:
        assert self.replayed, "PersistentDriver.replay() must run before poll"
        return self.driver.poll()

    def on_commit(self, time: int) -> None:
        events = self.recorder.take()
        if not events:
            # no events => reader/driver state unchanged; nothing to persist
            return
        # one atomic segment per commit with data: events + positional state
        # (reader state is O(sources), events are the deltas — the journal
        # grows with data volume, not with commit count)
        segment = {
            "events": events,
            "reader": (
                self.driver.reader.state()
                if hasattr(self.driver.reader, "state")
                else None
            ),
            "seq": self.driver._seq,
        }
        self.backend.append(self.name, pickle.dumps(segment, protocol=4))


# -- operator snapshots -------------------------------------------------------


#: Bumped whenever operator STATE derivation changes incompatibly — not
#: just the operator sequence (the class-name signature guards that).
#: Format 2: groupby group ids derive with salt b""/b"inst"
#: (ref_scalar-compatible; graph.py GroupbyNode._gkey_salt). Format 1
#: (implicit — older payloads carry no "format" key) salted group ids
#: with b"groupby"; its persisted groupby keys are unreachable under the
#: current derivation, so restoring one would strand every group under a
#: key no new row can touch while fresh rows silently rebuild duplicates
#: beside it. Stale snapshots are therefore REJECTED at restore.
STATE_FORMAT = 2


class OperatorSnapshotManager:
    """PersistenceMode.OPERATOR_PERSISTING: capture every operator's state
    at commit boundaries, restore it on startup and seek readers — no event
    replay, so resume cost is O(state), not O(history).

    Reference: src/persistence/operator_snapshot.rs (OperatorSnapshotWriter/
    Reader — consolidated state chunks at snapshot-interval boundaries) +
    tracker.rs (commit protocol). Node identity is the deterministic build
    order of the graph (the same Python logic rebuilds the same graph, like
    the reference rebuilding the dataflow per worker and matching persistent
    operator ids); a class-name signature guards against drift.

    ``snapshot_interval_ms=0`` snapshots at every commit — maximally
    durable, but each write serializes the FULL operator state. Large-state
    pipelines (big indexes, wide groupbys) should set an interval so
    snapshot cost amortizes over many commits; a final snapshot is always
    taken at end of run regardless of the interval.
    """

    def __init__(
        self,
        backend: PersistenceBackend,
        snapshot_interval_ms: int = 0,
        name: str = "operator-snapshot",
        retain: int = 1,
    ) -> None:
        self.backend = backend
        self.interval = snapshot_interval_ms / 1000.0
        self.name = name
        #: how many commit-boundary snapshots to keep addressable by time
        #: (``retain > 1`` additionally writes ``{name}-t{time}`` entries
        #: so mesh recovery can roll every survivor back to a COMMON
        #: commit, not just its own latest)
        self.retain = max(1, int(retain))
        self._ring: list[int] = []
        self._last_write = 0.0

    # -- capture -------------------------------------------------------------

    def _driver_state(self, driver: Any) -> dict:
        inner = getattr(driver, "driver", driver)
        reader = getattr(inner, "reader", None)
        return {
            "reader": reader.state()
            if reader is not None and hasattr(reader, "state")
            else None,
            "seq": getattr(inner, "_seq", 0),
            "per_source": getattr(inner, "_per_source_rows", {}),
            "done": getattr(inner, "done", False),
        }

    def _restore_driver(self, driver: Any, state: dict) -> None:
        inner = getattr(driver, "driver", driver)
        reader = getattr(inner, "reader", None)
        if state.get("reader") is not None and hasattr(reader, "restore_state"):
            reader.restore_state(state["reader"])
        inner._seq = state.get("seq", 0)
        inner._per_source_rows = dict(state.get("per_source", {}))

    @staticmethod
    def _scopes_of(scope: Any) -> list:
        return list(scope) if isinstance(scope, (list, tuple)) else [scope]

    def snapshot(self, scope: Any, drivers: list, time: int) -> None:
        """``scope`` may be a single scope or the list of per-worker scope
        replicas (ShardedGraphRunner) — each worker's operator state is
        captured separately, like the reference's per-worker snapshot
        writers (operator_snapshot.rs + tracker.rs per-worker storage)."""
        import pickle as _pickle

        scopes = self._scopes_of(scope)
        payload = {
            "format": STATE_FORMAT,
            "sigs": [[type(n).__name__ for n in s.nodes] for s in scopes],
            "per_worker": [[n.op_state() for n in s.nodes] for s in scopes],
            "drivers": [self._driver_state(d) for d in drivers],
            "time": time,
            # Graph-optimizer fingerprint (pathway_tpu.optimize): the exact
            # rewrites applied to this graph. Operator state written under a
            # rewritten graph (narrowed arities, fused chains) is only valid
            # under the SAME rewrites, so restore refuses on mismatch.
            "optimize": list(getattr(scopes[0], "_pw_opt_fingerprint", [])),
        }
        blob = _pickle.dumps(payload, protocol=4)
        self.backend.write(self.name, blob)
        if self.retain > 1:
            self.backend.write(f"{self.name}-t{time}", blob)
            if time not in self._ring:
                self._ring.append(time)
            while len(self._ring) > self.retain:
                stale = self._ring.pop(0)
                # overwrite with an empty blob: PersistenceBackend has no
                # delete, and restore treats empty as absent
                self.backend.write(f"{self.name}-t{stale}", b"")
        import time as _time

        self._last_write = _time.monotonic()

    def on_commit(self, scope: Any, drivers: list, time: int) -> None:
        """Throttled snapshot (interval 0 = every commit, like the
        reference's default snapshot quantization)."""
        import time as _time

        if self.interval and _time.monotonic() - self._last_write < self.interval:
            return
        self.snapshot(scope, drivers, time)

    # -- restore -------------------------------------------------------------

    def latest_time(self) -> int | None:
        """Peek the commit time of the latest snapshot without applying it
        (mesh recovery's rejoin handshake advertises this)."""
        import pickle as _pickle

        raw = self.backend.read(self.name)
        if not raw:
            return None
        try:
            payload = _pickle.loads(raw)
        except Exception:
            return None
        return int(payload.get("time", 0))

    def restore(
        self, scope: Any, drivers: list, at_time: int | None = None
    ) -> int | None:
        """Restore node + driver state; returns the snapshotted commit time
        when a snapshot was found and applied (the scheduler must resume
        *after* it so sink timestamps stay monotonic), else None.

        ``at_time`` selects a specific ring entry (``retain > 1``); the
        plain latest snapshot is used when it already carries that time."""
        import pickle as _pickle

        raw = self.backend.read(self.name)
        if at_time is not None and raw:
            try:
                if int(_pickle.loads(raw).get("time", 0)) != at_time:
                    raw = self.backend.read(f"{self.name}-t{at_time}")
            except Exception:
                raw = self.backend.read(f"{self.name}-t{at_time}")
        if not raw:
            if at_time is not None:
                raise ValueError(
                    f"no operator snapshot at commit time {at_time} "
                    f"under {self.name!r} (ring retains {self.retain})"
                )
            return None
        try:
            payload = _pickle.loads(raw)
        except Exception:  # truncated/corrupt snapshot: cold start
            return None
        fmt = payload.get("format", 1)
        if fmt != STATE_FORMAT:
            raise ValueError(
                f"operator snapshot has state format {fmt}; this build "
                f"writes format {STATE_FORMAT} (group-id salt change): "
                "restoring would resurrect state under stale keys — clear "
                "the persistence location or replay an input journal"
            )
        scopes = self._scopes_of(scope)
        if "per_worker" in payload:
            sigs = payload["sigs"]
            per_worker = payload["per_worker"]
        else:  # pre-multi-worker snapshot layout
            sigs = [payload["sig"]]
            per_worker = [payload["nodes"]]
        if [type(n).__name__ for n in scopes[0].nodes] != sigs[0]:
            raise ValueError(
                "operator snapshot does not match this graph (operator "
                "sequence changed — this includes toggling the graph "
                "optimizer, which fuses stateless chains into "
                "FusedChainNode; see PATHWAY_TPU_OPTIMIZE); clear the "
                "persistence location or use input-journal persistence "
                "across code changes"
            )
        want = list(getattr(scopes[0], "_pw_opt_fingerprint", []))
        got = list(payload.get("optimize", []))
        if want != got:
            raise ValueError(
                "operator snapshot was written under a different graph-"
                f"optimizer plan (snapshot applied {len(got)} rewrites, "
                f"this run applies {len(want)}): restoring would load "
                "state into operators with a different column layout or "
                "fusion boundary — rerun with the same "
                "PATHWAY_TPU_OPTIMIZE setting, or clear the persistence "
                "location / replay an input journal"
            )
        if len(per_worker) != len(scopes):
            # worker count changed: merge the old shards and re-split with
            # the sharded scheduler's own routing (reference: re-sharded
            # snapshot reads on worker-count change, persistence/config.rs:
            # 126-163)
            per_worker = _reshard_worker_states(per_worker, scopes)
        else:
            for s, sig in zip(scopes, sigs):
                if [type(n).__name__ for n in s.nodes] != sig:
                    raise ValueError(
                        "operator snapshot does not match this graph "
                        "(operator sequence changed); clear the persistence "
                        "location or use input-journal persistence across "
                        "code changes"
                    )
        for s, states in zip(scopes, per_worker):
            for node, state in zip(s.nodes, states):
                node.restore_op_state(state)
        for driver, state in zip(drivers, payload["drivers"]):
            self._restore_driver(driver, state)
        return int(payload.get("time", 0))


def _reshard_worker_states(
    per_worker: list[list[dict]], scopes: list
) -> list[list[dict]]:
    """Re-shard operator snapshots onto a different worker count.

    Merge every old worker's state per node, then split along the SAME
    routing the sharded scheduler applies to live deltas
    (engine/sharded.py ``partitioner``): groupbys by grouping values,
    joins by join key, deduplicate by instance, pinned operators whole to
    worker 0, everything else by row key. Node types whose extra state
    carries routing this function cannot reconstruct raise instead of
    guessing — input-journal persistence rescales those.
    """
    from pathway_tpu.engine.graph import (
        DeduplicateNode,
        GroupbyNode,
        InputSession,
        JoinNode,
        StaticSource,
    )
    from pathway_tpu.engine.sharded import _shard_of, partitioner

    n_new = len(scopes)
    # old worker 0 carried every node (sink chains included); workers > 0
    # stop at the shared graph — same layout on the new side, so iterate
    # worker-0's node list and let per-worker length guards handle the rest
    nodes = scopes[0].nodes
    n_nodes = min(len(nodes), len(per_worker[0]))

    def merged_state(i: int) -> dict:
        """Union of one node's state across the old workers."""
        base = dict(per_worker[0][i])
        for states in per_worker[1:]:
            if i >= len(states):
                continue
            for attr, val in states[i].items():
                cur = base.get(attr)
                if isinstance(cur, dict) and isinstance(val, dict):
                    merged = dict(cur)
                    merged.update(val)
                    base[attr] = merged
                elif (
                    isinstance(cur, list)
                    and isinstance(val, list)
                    and len(cur) == len(val)
                    and all(isinstance(x, dict) for x in cur + val)
                ):
                    base[attr] = [
                        {**a, **b} for a, b in zip(cur, val)
                    ]
                # scalars (watermarks, flags): worker 0's copy stands
        return base

    def empty_like(state: dict) -> dict:
        out = {}
        for attr, val in state.items():
            if isinstance(val, dict):
                out[attr] = {}
            elif isinstance(val, list) and all(
                isinstance(x, dict) for x in val
            ):
                out[attr] = [{} for _ in val]
            else:
                out[attr] = val
        return out

    def split_dict(d: dict, route) -> list[dict]:
        parts: list[dict] = [{} for _ in range(n_new)]
        for key, val in d.items():
            parts[route(key, val)][key] = val
        return parts

    out: list[list[dict]] = [[] for _ in range(n_new)]
    for i in range(n_nodes):
        node = nodes[i]
        merged = merged_state(i)
        shards = [empty_like(merged) for _ in range(n_new)]
        by_key = lambda key, _v: _shard_of(key, n_new)  # noqa: E731

        if isinstance(node, (StaticSource, InputSession)):
            # worker 0 keeps FULL source state; replicas hold key shards
            # (the _route_source invariant, engine/sharded.py)
            shards[0]["current"] = dict(merged["current"])
            for w in range(1, n_new):
                shards[w]["current"] = {
                    k: v
                    for k, v in merged["current"].items()
                    if _shard_of(k, n_new) == w
                }
        elif isinstance(node, GroupbyNode):
            shards_groups = split_dict(
                merged["groups"],
                lambda _k, entry: _shard_of(tuple(entry[0]), n_new),
            )
            n_by = len(node.by_cols)
            shards_current = split_dict(
                merged["current"],
                lambda _k, row: _shard_of(tuple(row[:n_by]), n_new),
            )
            for w in range(n_new):
                shards[w]["groups"] = shards_groups[w]
                shards[w]["current"] = shards_current[w]
        elif isinstance(node, JoinNode):
            for attr, cols in (
                ("left_arr", node.left_on),
                ("right_arr", node.right_on),
            ):
                parts = split_dict(
                    merged[attr], lambda jk, _v: _shard_of(jk, n_new)
                )
                for w in range(n_new):
                    shards[w][attr] = parts[w]
            lcols = node.left_on
            rcols = node.right_on
            l_arity = node.inputs[0].arity

            def route_join_row(_k, row):
                jk = tuple(row[c] for c in lcols)
                if any(v is None for v in jk):
                    # unmatched-right padding (RIGHT/OUTER joins): the
                    # left prefix is all None — route by the right-side
                    # key, which is where the live partitioner owns it
                    jk = tuple(row[l_arity + c] for c in rcols)
                return _shard_of(jk, n_new)

            parts = split_dict(merged["current"], route_join_row)
            for w in range(n_new):
                shards[w]["current"] = parts[w]
        elif isinstance(node, DeduplicateNode):
            icols = node.instance_cols
            for attr in ("accepted", "current"):
                parts = split_dict(
                    merged[attr],
                    lambda _k, row: _shard_of(
                        tuple(row[c] for c in icols), n_new
                    ),
                )
                for w in range(n_new):
                    shards[w][attr] = parts[w]
        elif partitioner(node, 0, n_new) is None:
            # pinned operator: whole state lives on worker 0
            shards[0] = merged
        else:
            # key-routed node: current and input mirrors shard by row key;
            # any OTHER populated container state has routing this generic
            # path cannot reconstruct
            for attr, val in merged.items():
                if attr in ("current", "_mirrors"):
                    continue
                populated = (
                    bool(val)
                    if isinstance(val, (dict, list, set))
                    else False
                )
                if populated:
                    raise ValueError(
                        f"operator snapshot cannot be re-sharded: node "
                        f"{type(node).__name__} carries {attr!r} state "
                        "with unknown routing; resume with the original "
                        f"worker count ({len(per_worker)}) or use input-"
                        "journal persistence (PersistenceMode.PERSISTING) "
                        "to change worker counts"
                    )
            parts = split_dict(merged["current"], by_key)
            for w in range(n_new):
                shards[w]["current"] = parts[w]
            if isinstance(merged.get("_mirrors"), list):
                mirror_parts = [
                    split_dict(m, by_key) for m in merged["_mirrors"]
                ]
                for w in range(n_new):
                    shards[w]["_mirrors"] = [
                        mp[w] for mp in mirror_parts
                    ]
        for w in range(n_new):
            out[w].append(shards[w])
    return out


def reshard_process_snapshots(
    backend: PersistenceBackend,
    old_processes: int,
    new_processes: int,
    threads: int,
    scopes: list,
    *,
    n_shared: int,
) -> dict:
    """Rewrite the per-process operator snapshots of an N-process mesh
    for an M-process mesh (the ``MeshSupervisor.rescale`` state step).

    Every process of a quiesced mesh left ``operator-snapshot-p{pid}``
    at the same commit boundary.  This merges them into the global
    worker-state list (global worker id = ``pid * threads + scope_idx``,
    the mesh exchange numbering), re-splits it through
    :func:`_reshard_worker_states` — i.e. through the SAME routing the
    live exchange uses, so a re-sharded groupby lands exactly where its
    next delta will — and writes one snapshot per NEW process.  Scale-in
    merges the departing processes' shards; scale-out deals new shards
    to the added processes.  Stale snapshots of processes beyond the new
    count are blanked so a later scale-OUT cannot resurrect them.

    ``scopes`` are the helper process's own worker scopes (scope 0 full
    with the sink chain, replicas shared-only up to ``n_shared``) —
    the graph is rebuilt by re-running the program, exactly like a
    restarted worker.  Returns a report dict (old/new sizes, commit
    time, exact moved-key count from ``engine/routing.reshard_moves``).
    """
    import pickle as _pickle

    from pathway_tpu.engine.graph import InputSession, StaticSource
    from pathway_tpu.engine.routing import reshard_moves

    if old_processes < 1 or new_processes < 1:
        raise ValueError("process counts must be >= 1")

    def _load(name: str) -> dict | None:
        raw = backend.read(name)
        if not raw:
            return None
        try:
            return _pickle.loads(raw)
        except Exception:
            return None

    payloads: list[dict] = []
    for p in range(old_processes):
        payload = _load(f"operator-snapshot-p{p}")
        if payload is None:
            raise ValueError(
                f"rescale: no operator snapshot for process {p} "
                f"(expected {old_processes} quiesced snapshots)"
            )
        payloads.append(payload)
    for p, payload in enumerate(payloads):
        fmt = payload.get("format", 1)
        if fmt != STATE_FORMAT:
            raise ValueError(
                f"rescale: process {p} snapshot has state format {fmt}; "
                f"this build writes format {STATE_FORMAT}"
            )
    t_common = min(int(pl.get("time", 0)) for pl in payloads)
    for p, payload in enumerate(payloads):
        if int(payload.get("time", 0)) != t_common:
            ring = _load(f"operator-snapshot-p{p}-t{t_common}")
            if ring is None:
                raise ValueError(
                    f"rescale: process {p} has no snapshot at the "
                    f"common commit time {t_common} (ring rotated?)"
                )
            payloads[p] = ring
    base = payloads[0]
    fp = list(base.get("optimize", []))
    for p, payload in enumerate(payloads[1:], start=1):
        if list(payload.get("optimize", [])) != fp:
            raise ValueError(
                f"rescale: process {p} snapshot was written under a "
                "different graph-optimizer plan than process 0"
            )
    if list(getattr(scopes[0], "_pw_opt_fingerprint", [])) != fp:
        raise ValueError(
            "rescale: snapshots were written under a different graph-"
            "optimizer plan than this process applies — rerun with the "
            "same PATHWAY_TPU_OPTIMIZE setting"
        )
    full_sig = [type(n).__name__ for n in scopes[0].nodes]
    if base["sigs"][0] != full_sig:
        raise ValueError(
            "rescale: operator snapshot does not match this graph "
            "(operator sequence changed); clear the persistence "
            "location instead of rescaling across code changes"
        )
    shared_sig = full_sig[:n_shared]
    for p, payload in enumerate(payloads[1:], start=1):
        if payload["sigs"][0][: len(shared_sig)] != shared_sig:
            raise ValueError(
                f"rescale: process {p} snapshot does not match the "
                "shared graph prefix"
            )

    global_per_worker: list[list[dict]] = []
    for payload in payloads:
        global_per_worker.extend(payload["per_worker"])
    virtual = [scopes[0]] * (new_processes * threads)
    new_per_worker = _reshard_worker_states(global_per_worker, virtual)

    # exact state-transfer volume: source rows are fully mirrored on old
    # worker 0, so its key set is the authoritative row population
    src_keys: list = []
    for i, node in enumerate(scopes[0].nodes[:n_shared]):
        if isinstance(node, (StaticSource, InputSession)):
            cur = (
                global_per_worker[0][i].get("current")
                if i < len(global_per_worker[0])
                else None
            )
            if isinstance(cur, dict):
                src_keys.extend(cur.keys())
    moved = reshard_moves(
        src_keys, old_processes * threads, new_processes * threads
    )

    for q in range(new_processes):
        states = new_per_worker[q * threads:(q + 1) * threads]
        if q == 0:
            sigs = [[type(n).__name__ for n in s.nodes] for s in scopes]
            states = [states[0]] + [st[:n_shared] for st in states[1:]]
            drivers = base.get("drivers", [])
        else:
            sigs = [list(shared_sig) for _ in range(threads)]
            states = [st[:n_shared] for st in states]
            drivers = []
        payload = {
            "format": STATE_FORMAT,
            "sigs": sigs,
            "per_worker": states,
            "drivers": drivers,
            "time": t_common,
            "optimize": fp,
        }
        blob = _pickle.dumps(payload, protocol=4)
        backend.write(f"operator-snapshot-p{q}", blob)
        backend.write(f"operator-snapshot-p{q}-t{t_common}", blob)
    for q in range(new_processes, old_processes):
        # blank departed processes' snapshots: a later scale-OUT must
        # never rejoin from this run's stale shard
        backend.write(f"operator-snapshot-p{q}", b"")
    return {
        "old_processes": old_processes,
        "new_processes": new_processes,
        "threads": threads,
        "time": t_common,
        "source_rows": len(src_keys),
        "moved_keys": moved,
    }


class ObjectStoreBackend(PersistenceBackend):
    """Persistence over an S3-shaped object store (reference:
    src/persistence/backends/s3.rs). ``client`` needs get_object/put_object/
    list_objects — the same seam as pw.io.s3, so boto3 or the in-memory
    DictObjectStore drop in. Objects can't append, so the journal keeps an
    on-store chunk counter per stream (the reference chunks too)."""

    def __init__(self, client: Any, prefix: str = "pathway-persistence") -> None:
        self.client = client
        self.prefix = prefix.rstrip("/")
        self._chunk_counts: dict[str, int] = {}

    def _key(self, name: str, chunk: int | None = None) -> str:
        from urllib.parse import quote

        base = f"{self.prefix}/{quote(name, safe='')}"
        return base if chunk is None else f"{base}/chunk-{chunk:09d}"

    def _chunks(self, name: str) -> list[str]:
        return sorted(
            k for k, _sig in self.client.list_objects(self._key(name) + "/")
        )

    def append(self, name: str, payload: bytes) -> None:
        n = self._chunk_counts.get(name)
        if n is None:
            n = len(self._chunks(name))
        self.client.put_object(self._key(name, n), payload)
        self._chunk_counts[name] = n + 1

    def write(self, name: str, payload: bytes) -> None:
        self.client.put_object(self._key(name), payload)

    def read(self, name: str) -> bytes:
        direct = self._key(name)
        chunks = self._chunks(name)
        if chunks:
            return b"".join(self.client.get_object(k) for k in chunks)
        try:
            return self.client.get_object(direct)
        except Exception:  # noqa: BLE001 — dict stores raise KeyError, boto3
            # raises botocore ClientError(NoSuchKey); either way: cold start
            return b""

    def exists(self, name: str) -> bool:
        if self._chunks(name):
            return True
        try:
            self.client.get_object(self._key(name))
            return True
        except Exception:  # noqa: BLE001 — see read()
            return False
