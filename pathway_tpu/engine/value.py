"""Engine value system: runtime types, keys, error poisoning.

TPU-native rebuild of the reference engine's value layer
(reference: src/engine/value.rs — Key u128 xxh3 at value.rs:41, Value enum at
value.rs:207, Type at value.rs:507, ShardPolicy at value.rs:94). This is a new
implementation: keys are 128-bit ints derived from a stable BLAKE2b-128 of a
deterministic serialization (the contract is "stable 128-bit content hash",
not the exact xxh3 bit pattern), values are plain Python/NumPy objects tagged
by :class:`Type`, and ``ERROR`` is a poisoning sentinel that propagates
through expressions instead of raising (reference: src/engine/error.rs).
"""

from __future__ import annotations

import datetime
import enum
import hashlib
import json as _json
import math
import struct
from typing import Any, Iterable

import numpy as np

from pathway_tpu.native import kernels as _native

__all__ = [
    "Type",
    "Kind",
    "Pointer",
    "Error",
    "ERROR",
    "Json",
    "PyObjectWrapper",
    "Duration",
    "DateTimeNaive",
    "DateTimeUtc",
    "hash_values",
    "hash_values_batch",
    "ref_scalar",
    "unsafe_make_pointer",
    "value_type_of",
    "is_error",
    "SHARD_MASK",
]


class Type(enum.Enum):
    """Engine column types (reference: src/engine/value.rs:507)."""

    ANY = "Any"
    NONE = "None"
    BOOL = "Bool"
    INT = "Int"
    FLOAT = "Float"
    POINTER = "Pointer"
    STRING = "String"
    BYTES = "Bytes"
    DATE_TIME_NAIVE = "DateTimeNaive"
    DATE_TIME_UTC = "DateTimeUtc"
    DURATION = "Duration"
    ARRAY = "Array"
    JSON = "Json"
    TUPLE = "Tuple"
    LIST = "List"
    PY_OBJECT_WRAPPER = "PyObjectWrapper"
    FUTURE = "Future"

    def __repr__(self) -> str:
        return f"Type.{self.name}"


class Kind(enum.Enum):
    """Value kinds as seen by the engine (scalar vs error)."""

    VALUE = 0
    ERROR = 1


class Error:
    """Singleton poisoning sentinel (reference: Value::Error, src/engine/value.rs:228).

    Any expression evaluated over an ``ERROR`` operand yields ``ERROR`` rather
    than raising; rows carrying errors are routed to error logs and can be
    filtered with ``remove_errors``.
    """

    _instance: "Error | None" = None

    def __new__(cls) -> "Error":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Error"

    def __bool__(self) -> bool:
        raise ValueError("cannot convert error value to bool")

    def __hash__(self) -> int:
        return 0x9E3779B97F4A7C15

    def __reduce__(self):
        return (Error, ())


ERROR = Error()


def is_error(value: Any) -> bool:
    return value is ERROR or isinstance(value, Error)


SHARD_MASK = (1 << 64) - 1


class Pointer(int):
    """A 128-bit row key (reference: Key(u128), src/engine/value.rs:41).

    Subclasses ``int`` so it hashes/compares natively; rendering is the
    compact ``^BASE32``-style form used in printed tables.
    """

    __slots__ = ()

    _ALPHABET = "0123456789ABCDEFGHIJKLMNOPQRSTUV"

    def __new__(cls, value: int) -> "Pointer":
        return super().__new__(cls, int(value) & ((1 << 128) - 1))

    def shard(self, nshards: int) -> int:
        """Shard routing: high 64 bits modulo shard count (data parallelism)."""
        return (int(self) >> 64) % nshards

    def __repr__(self) -> str:
        n = int(self)
        if n == 0:
            return "^0"
        digits = []
        while n:
            digits.append(self._ALPHABET[n & 31])
            n >>= 5
        return "^" + "".join(reversed(digits))

    __str__ = __repr__


class Json:
    """JSON value wrapper (reference: Value::Json)."""

    __slots__ = ("value",)

    def __init__(self, value: Any) -> None:
        if isinstance(value, Json):
            value = value.value
        self.value = value

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, Json):
            return self.value == other.value
        return self.value == other

    def __hash__(self) -> int:
        return hash(_json.dumps(self.value, sort_keys=True, default=str))

    def __repr__(self) -> str:
        return _json.dumps(self.value, default=str)

    def as_int(self) -> int | None:
        return int(self.value) if isinstance(self.value, (int, float)) else None

    def as_float(self) -> float | None:
        return float(self.value) if isinstance(self.value, (int, float)) else None

    def as_str(self) -> str | None:
        return self.value if isinstance(self.value, str) else None

    def as_bool(self) -> bool | None:
        return self.value if isinstance(self.value, bool) else None

    def as_list(self) -> list | None:
        return self.value if isinstance(self.value, list) else None

    def as_dict(self) -> dict | None:
        return self.value if isinstance(self.value, dict) else None

    def __getitem__(self, item: Any) -> "Json":
        return Json(self.value[item])

    def get(self, item: Any, default: Any = None) -> "Json | None":
        if isinstance(self.value, dict):
            got = self.value.get(item, _SENTINEL)
            if got is _SENTINEL:
                return default
            return Json(got)
        if isinstance(self.value, list) and isinstance(item, int):
            if -len(self.value) <= item < len(self.value):
                return Json(self.value[item])
            return default
        return default

    def __len__(self) -> int:
        return len(self.value)

    def __iter__(self):
        for item in self.value:
            yield Json(item)


_SENTINEL = object()


class PyObjectWrapper:
    """Opaque Python object carried through the engine (Value::PyObjectWrapper)."""

    __slots__ = ("value", "_serializer")

    def __init__(self, value: Any, *, serializer: Any = None) -> None:
        self.value = value
        self._serializer = serializer

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, PyObjectWrapper) and self.value == other.value

    def __hash__(self) -> int:
        try:
            return hash(self.value)
        except TypeError:
            return id(self.value)

    def __repr__(self) -> str:
        return f"pw.wrap_py_object({self.value!r})"


# Date/time: thin aliases over stdlib types. Naive vs UTC is tracked at the
# dtype level (reference keeps separate Value variants, src/engine/time.rs).
DateTimeNaive = datetime.datetime
DateTimeUtc = datetime.datetime
Duration = datetime.timedelta


# ---------------------------------------------------------------------------
# Stable hashing → 128-bit keys
# ---------------------------------------------------------------------------

_H_NONE = b"\x00"
_H_BOOL = b"\x01"
_H_INT = b"\x02"
_H_FLOAT = b"\x03"
_H_POINTER = b"\x04"
_H_STRING = b"\x05"
_H_BYTES = b"\x06"
_H_TUPLE = b"\x07"
_H_ARRAY = b"\x08"
_H_DT = b"\x09"
_H_DUR = b"\x0a"
_H_JSON = b"\x0b"
_H_PYOBJ = b"\x0c"
_H_ERROR = b"\x0d"


def _feed(h: "hashlib._Hash", value: Any) -> None:
    if value is None:
        h.update(_H_NONE)
    elif isinstance(value, Error):
        h.update(_H_ERROR)
    elif isinstance(value, Pointer):
        h.update(_H_POINTER)
        h.update(int(value).to_bytes(16, "little"))
    elif isinstance(value, bool):
        h.update(_H_BOOL)
        h.update(b"\x01" if value else b"\x00")
    elif isinstance(value, (int, np.integer)):
        h.update(_H_INT)
        h.update(int(value).to_bytes(16, "little", signed=True))
    elif isinstance(value, (float, np.floating)):
        f = float(value)
        if math.isnan(f) or math.isinf(f):
            h.update(_H_FLOAT)
            h.update(struct.pack("<d", f))
        elif abs(f) < 2**63 and f == int(f):
            # ints and equal floats hash alike, matching engine semantics
            h.update(_H_INT)
            h.update(int(f).to_bytes(16, "little", signed=True))
        else:
            h.update(_H_FLOAT)
            h.update(struct.pack("<d", f))
    elif isinstance(value, str):
        b = value.encode()
        h.update(_H_STRING)
        h.update(len(b).to_bytes(8, "little"))
        h.update(b)
    elif isinstance(value, bytes):
        h.update(_H_BYTES)
        h.update(len(value).to_bytes(8, "little"))
        h.update(value)
    elif isinstance(value, tuple) or isinstance(value, list):
        h.update(_H_TUPLE)
        h.update(len(value).to_bytes(8, "little"))
        for item in value:
            _feed(h, item)
    elif isinstance(value, np.ndarray):
        h.update(_H_ARRAY)
        h.update(str(value.dtype).encode())
        h.update(str(value.shape).encode())
        h.update(np.ascontiguousarray(value).tobytes())
    elif isinstance(value, datetime.datetime):
        h.update(_H_DT)
        h.update(value.isoformat().encode())
    elif isinstance(value, datetime.timedelta):
        h.update(_H_DUR)
        h.update(struct.pack("<q", round(value.total_seconds() * 1_000_000_000)))
    elif isinstance(value, Json):
        h.update(_H_JSON)
        h.update(_json.dumps(value.value, sort_keys=True, default=str).encode())
    elif isinstance(value, PyObjectWrapper):
        h.update(_H_PYOBJ)
        _feed(h, repr(value.value))
    else:
        h.update(_H_PYOBJ)
        _feed(h, repr(value))


#: pre-personalized hasher, cloned per digest — blake2b parameter-block
#: construction costs more than copy(), and every key derivation pays it
_BASE_HASHER = hashlib.blake2b(digest_size=16, person=b"pw-tpu-key")


def _digest16(values: Iterable[Any], salt: bytes) -> bytes:
    """The 16-byte little-endian digest behind :func:`hash_values`.

    Digest-identical fast path: common scalar types append to one buffer
    flushed in a single ``update`` (join/groupby key derivation calls this
    per output row — the per-value ``_feed`` dispatch dominated join time).
    """
    h = _BASE_HASHER.copy()
    buf = bytearray(salt)
    for value in values:
        t = type(value)
        if t is Pointer:
            buf += _H_POINTER
            buf += int.to_bytes(value, 16, "little")
        elif t is int:
            buf += _H_INT
            buf += value.to_bytes(16, "little", signed=True)
        elif t is str:
            b = value.encode()
            buf += _H_STRING
            buf += len(b).to_bytes(8, "little")
            buf += b
        elif t is bool:
            buf += _H_BOOL
            buf += b"\x01" if value else b"\x00"
        elif t is float:
            if math.isnan(value) or math.isinf(value):
                buf += _H_FLOAT
                buf += struct.pack("<d", value)
            elif abs(value) < 2**63 and value == int(value):
                buf += _H_INT
                buf += int(value).to_bytes(16, "little", signed=True)
            else:
                buf += _H_FLOAT
                buf += struct.pack("<d", value)
        else:
            if buf:
                h.update(bytes(buf))
                buf.clear()
            _feed(h, value)
    if buf:
        h.update(bytes(buf))
    return h.digest()


def hash_values(values: Iterable[Any], *, salt: bytes = b"") -> Pointer:
    """Stable 128-bit key from a sequence of values (Key::for_values analog)."""
    return Pointer(int.from_bytes(_digest16(values, salt), "little"))


def hash_values_batch(
    rows: "Iterable[Iterable[Any]]",
    *,
    salt: bytes = b"",
    on_type_error: str = "raise",
) -> np.ndarray:
    """Digest matrix for many value tuples in ONE call: row ``i`` of the
    returned ``(len(rows), 16)`` uint8 array is the little-endian digest of
    ``hash_values(rows[i], salt=salt)``.

    The shard-routing kernel (engine/routing.py) feeds DISTINCT key
    representatives through here, so routing an object column hashes once
    per call instead of once per row at Python-closure granularity, and the
    byte matrix flows straight into the vectorized 128-bit mod
    (routing.mod_u128_bytes) without boxing a Pointer per value.

    ``on_type_error="repr"`` re-hashes ``repr`` of the row's values when a
    digest raises TypeError — the exact fallback the per-row partitioners
    (sharded._shard_of) use, kept here so batch and scalar paths cannot
    drift.

    When the native kernels are loaded, list/ndarray inputs run through
    ``hash_tuples_batch`` — one C call serializes and digests every row;
    values outside the native serializer's exact-type set come back here
    per row through the fallback closure, so both paths stay
    digest-identical by construction (enforced by tests/test_native_parity).
    """
    repr_fallback = on_type_error == "repr"
    if _native is not None and hasattr(_native, "hash_tuples_batch") and (
        isinstance(rows, list)
        or (
            isinstance(rows, np.ndarray)
            and rows.dtype == object
            and rows.ndim == 1
            and rows.flags.c_contiguous
        )
    ):

        def _row_fallback(row: Any) -> bytes:
            try:
                return _digest16(row, salt)
            except TypeError:
                if not repr_fallback:
                    raise
                return _digest16(tuple(repr(v) for v in row), salt)

        return _native.hash_tuples_batch(
            rows, salt, False, Pointer, ERROR, _row_fallback
        )
    return _hash_values_batch_py(rows, salt=salt, on_type_error=on_type_error)


def _hash_values_batch_py(
    rows: "Iterable[Iterable[Any]]",
    *,
    salt: bytes = b"",
    on_type_error: str = "raise",
) -> np.ndarray:
    """Pure-Python row loop behind :func:`hash_values_batch` — THE
    reference behavior the native kernel must reproduce bit for bit."""
    repr_fallback = on_type_error == "repr"
    out = bytearray()
    n = 0
    for row in rows:
        try:
            d = _digest16(row, salt)
        except TypeError:
            if not repr_fallback:
                raise
            d = _digest16(tuple(repr(v) for v in row), salt)
        out += d
        n += 1
    return np.frombuffer(bytes(out), np.uint8).reshape(n, 16)


def _hash_values_slow(values: Iterable[Any], *, salt: bytes = b"") -> Pointer:
    """Reference implementation (kept for digest-equality tests)."""
    h = hashlib.blake2b(digest_size=16, person=b"pw-tpu-key")
    if salt:
        h.update(salt)
    for value in values:
        _feed(h, value)
    return Pointer(int.from_bytes(h.digest(), "little"))


def ref_scalar(*values: Any, instance: Any = None) -> Pointer:
    """Derive a pointer from scalar values (python_api.rs ref_scalar :3373)."""
    if instance is not None:
        return hash_values(tuple(values) + (instance,), salt=b"inst")
    return hash_values(values)


def unsafe_make_pointer(value: int) -> Pointer:
    return Pointer(value)


_NUMPY_INT_KINDS = "iu"


def value_type_of(value: Any) -> Type:
    """Runtime type tag of a value."""
    if value is None:
        return Type.NONE
    if isinstance(value, Error):
        return Type.ANY
    if isinstance(value, Pointer):
        return Type.POINTER
    if isinstance(value, bool) or isinstance(value, np.bool_):
        return Type.BOOL
    if isinstance(value, (int, np.integer)):
        return Type.INT
    if isinstance(value, (float, np.floating)):
        return Type.FLOAT
    if isinstance(value, str):
        return Type.STRING
    if isinstance(value, bytes):
        return Type.BYTES
    if isinstance(value, datetime.datetime):
        return Type.DATE_TIME_UTC if value.tzinfo is not None else Type.DATE_TIME_NAIVE
    if isinstance(value, datetime.timedelta):
        return Type.DURATION
    if isinstance(value, np.ndarray):
        return Type.ARRAY
    if isinstance(value, Json):
        return Type.JSON
    if isinstance(value, tuple):
        return Type.TUPLE
    if isinstance(value, list):
        return Type.LIST
    if isinstance(value, PyObjectWrapper):
        return Type.PY_OBJECT_WRAPPER
    return Type.ANY


def rows_differ(a: "tuple | None", b: "tuple | None") -> bool:
    """Row inequality that tolerates numpy-array cells (plain ``!=`` raises
    'truth value is ambiguous' on arrays). None = absent row. The common
    all-scalar row stays on the C tuple compare; only rows actually holding
    arrays take the per-cell path."""
    if a is b:
        return False
    if a is None or b is None:
        return True
    try:
        return a != b
    except ValueError:  # some cell is a numpy array
        pass
    if len(a) != len(b):
        return True
    for x, y in zip(a, b):
        if x is y:
            continue
        try:
            if x != y:
                return True
        except ValueError:  # numpy broadcast comparison
            import numpy as np

            if not np.array_equal(x, y):
                return True
    return False
