"""TPU-native engine: incremental keyed update streams + JAX device compute.

Replaces the reference's Rust/timely engine (src/engine/) with a host-side
commit scheduler and device-side JAX operators.
"""

from pathway_tpu.engine.batch import DeltaBatch
from pathway_tpu.engine.graph import (
    InputSession,
    JoinKind,
    Node,
    Scheduler,
    Scope,
)
from pathway_tpu.engine.reducers import Reducer, ReducerKind, make_reducer
from pathway_tpu.engine.value import (
    ERROR,
    DateTimeNaive,
    DateTimeUtc,
    Duration,
    Error,
    Json,
    Pointer,
    PyObjectWrapper,
    Type,
    hash_values,
    is_error,
    ref_scalar,
    unsafe_make_pointer,
)

__all__ = [
    "DeltaBatch",
    "InputSession",
    "JoinKind",
    "Node",
    "Scheduler",
    "Scope",
    "Reducer",
    "ReducerKind",
    "make_reducer",
    "ERROR",
    "DateTimeNaive",
    "DateTimeUtc",
    "Duration",
    "Error",
    "Json",
    "Pointer",
    "PyObjectWrapper",
    "Type",
    "hash_values",
    "is_error",
    "ref_scalar",
    "unsafe_make_pointer",
]
