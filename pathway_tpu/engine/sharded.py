"""Multi-worker execution: key-sharded scopes with inter-operator exchange.

Reference worker model (src/engine/dataflow/config.rs:63-120,
value.rs:94-130 Key::shard, docs worker-architecture.md:36-47): every
worker runs the IDENTICAL dataflow over a hash-partition of the key space;
records cross workers at exchange points before stateful operators, and
single-threaded sinks run on worker 0.

Here each logical worker owns a full engine Scope built from the same
graph logic (the reference re-executes the Python logic per worker,
python_api.rs:3329). The sharded scheduler propagates all scopes in
lockstep; when operator A on worker w emits a batch for consumer B, the
batch is partitioned by B's co-location key and delivered to B's replica
on the owning worker:

- groupby/deduplicate: by grouping/instance values
- join: per side, by the join-key columns
- ix: lookups route to the owner of the pointed-at row
- temporal/iterate/external-index/subscribe/output: worker 0 (their state
  is global — watermarks, fixed-points, as-of-now indexes; the reference
  similarly pins non-partitionable sinks to one worker)
- everything else: by row key

In-process today; the exchange seam is where ICI/DCN collectives slot in
for multi-host (SURVEY §2.10 mapping).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Sequence

from pathway_tpu.engine.batch import (
    DeltaBatch,
    apply_batch_to_state,
    columnarize_entries,
)
from pathway_tpu.engine.device import VECTOR_THRESHOLD
from pathway_tpu.engine.graph import (
    DeduplicateNode,
    ErrorLogNode,
    GroupbyNode,
    InputSession,
    IxNode,
    JoinNode,
    Node,
    Scope,
    SortNode,
    StaticSource,
    SubscribeNode,
)
# the vectorized routing math lives in engine/routing.py; `_shard_of` and
# `_object_codes` are re-exported here because the partitioner closures
# below and older call sites (engine/distributed.py, tests) address them
# through this module
from pathway_tpu.engine.routing import (  # noqa: F401 — re-exports
    EXCHANGE_STATS,
    _object_codes,
    _shard_of,
    batch_shards,
    columnar_shards,
    entry_shards,
    shards_of_values,
)
from pathway_tpu.engine.value import Pointer

Entry = tuple

#: debug cross-check: recompute routing for every elided delivery and
#: assert the whole batch is already co-located (optimizer soundness net)
_VERIFY_ELISION = os.environ.get("PATHWAY_TPU_VERIFY_ELISION") == "1"


def _assert_colocated(
    consumer: Node, port: int, out: DeltaBatch, worker: int, n: int
) -> None:
    shards = batch_shards(partition_rule(consumer, port), out, n)
    if shards is not None and len(shards) and not (shards == worker).all():
        raise AssertionError(
            f"elided exchange into {consumer.name}#{consumer.index} "
            f"(port {port}) moved rows off worker {worker}"
        )


def partition_rule(consumer: Node, port: int) -> tuple:
    """ONE classification of how entries entering ``consumer`` on ``port``
    pick their worker — consumed by BOTH the per-row closure builder and
    the vectorized columnar exchange, so the two can never drift:

    - ``("pin",)``        everything to worker 0 (globally-stateful op)
    - ``("key",)``        by row key (full 128-bit pointer mod n)
    - ``("cols", cols)``  by ``hash(tuple(row[c] for c in cols))``
    - ``("col", c)``      by the bare value ``row[c]`` (c None = constant)
    """
    from pathway_tpu.engine import temporal as _temporal
    from pathway_tpu.engine.external_index import ExternalIndexNode
    from pathway_tpu.engine.graph import RecomputeNode
    from pathway_tpu.engine.iterate import IterateNode

    if isinstance(consumer, GroupbyNode):
        return ("cols", list(consumer.by_cols))
    if isinstance(consumer, DeduplicateNode):
        return ("cols", list(consumer.instance_cols))
    if isinstance(consumer, JoinNode):
        return (
            "cols",
            list(consumer.left_on if port == 0 else consumer.right_on),
        )
    if isinstance(consumer, SortNode):
        return ("col", consumer.instance_col)
    if isinstance(consumer, IxNode):
        return ("col", consumer.key_col) if port == 0 else ("key",)
    if isinstance(
        consumer,
        (
            SubscribeNode,
            ErrorLogNode,
            ExternalIndexNode,
            IterateNode,
            RecomputeNode,  # row transformers consume whole input states
            _temporal.GradualBroadcastNode,  # needs the threshold triplet
            _temporal.BufferNode,
            _temporal.ForgetNode,
            _temporal.FreezeNode,
            _temporal.SessionAssignNode,
            _temporal.IntervalJoinNode,
            _temporal.AsofJoinNode,
            _temporal.AsofNowJoinNode,
        ),
    ):
        return ("pin",)  # global state: pin to worker 0
    return ("key",)


def partitioner(
    consumer: Node, port: int, n_workers: int
) -> Callable[[Pointer, tuple], int] | None:
    """Per-row closure for :func:`partition_rule`; None = worker 0."""
    rule = partition_rule(consumer, port)
    kind = rule[0]
    if kind == "pin":
        return None
    if kind == "cols":
        cols = rule[1]

        def by_cols(key: Pointer, row: tuple) -> int:
            return _shard_of(tuple(row[c] for c in cols), n_workers)

        return by_cols
    if kind == "col":
        col = rule[1]

        def by_col(key: Pointer, row: tuple) -> int:
            return _shard_of(
                row[col] if col is not None else None, n_workers
            )

        return by_col

    def by_key(key: Pointer, row: tuple) -> int:
        return _shard_of(key, n_workers)

    return by_key


class ShardedScheduler:
    """Lockstep commit pump over N identically-built scopes."""

    def __init__(
        self,
        scopes: Sequence[Scope],
        probe: bool = False,
        optimize: bool = True,
    ) -> None:
        self.scopes = list(scopes)
        self.n = len(self.scopes)
        for scope in self.scopes:
            # replica `current` holds key shards: state-peeking operators
            # (zip/ix/update/iterate) must use their own input mirrors
            scope.sharded = True
        self.time = 0
        self.probe = probe
        #: the pump thread inserts per-operator entries while the live
        #: monitoring thread snapshots the dict — serialize the inserts
        self._stats_lock = threading.Lock()
        #: node index -> OperatorStats aggregated ACROSS workers (the
        #: monitoring surface reads .scope/.stats like the single Scheduler)
        self.stats: dict[int, Any] = {}  # guarded-by: self._stats_lock
        if probe:
            from pathway_tpu.internals import metrics as _metrics

            self._queue_gauge = _metrics.REGISTRY.gauge(
                "pathway_queue_depth",
                "operators with pending delta batches (backpressure)",
            )
        sigs = [
            [type(node).__name__ for node in scope.nodes]
            for scope in self.scopes
        ]
        # worker 0 may carry extra TRAILING nodes: sinks attach there only
        # (single-threaded sinks, reference data_storage.rs:611)
        for w, sig in enumerate(sigs[1:], start=1):
            if sigs[0][: len(sig)] != sig:
                raise ValueError(
                    f"worker {w} scope diverged: the graph logic must build "
                    "the identical operator sequence on every worker"
                )
        #: (producer, consumer, port) edges the optimizer proved exchange-
        #: redundant — _deliver pushes those straight to the co-located
        #: replica (rewrites every replica scope in place, identically)
        self._elided: set = set()
        if optimize:
            from pathway_tpu.optimize import optimize_scopes

            self._elided = optimize_scopes(self.scopes)
        # partition function cache per (consumer index, port)
        self._parts: dict[tuple[int, int], Any] = {}

    def _partition_fn(self, consumer: Node, port: int):
        key = (consumer.index, port)
        fn = self._parts.get(key, False)
        if fn is False:
            fn = partitioner(consumer, port, self.n)
            self._parts[key] = fn
        return fn

    def _columnar_shards(
        self, consumer: Node, port: int, out: DeltaBatch
    ):
        return columnar_shards(
            partition_rule(consumer, port), out.columns, self.n
        )

    def _deliver(
        self, worker: int, producer: Node, out: DeltaBatch
    ) -> None:
        """Exchange step: split ``out`` per consumer and push each part to
        the consumer's replica on the owning worker. The consumer topology
        comes from worker 0's scope — the superset, since sinks attach
        there only.

        Delivery planes, in decision order (every branch counts exactly
        one of elided/host/collective plus ``repartitions``):

        1. optimizer-elided edges skip all routing (PR 4) — checked
           BEFORE the collective is even considered;
        2. pinned consumers take the whole batch on worker 0 (host);
        3. columnar batches on a device-colocated mesh may repartition
           through engine/collective_exchange (one all-to-all instead of
           n gather+push hops) — a decline falls through to
        4. the host columnar gather split, then
        5. the row-entry fallback.
        """
        import time as _walltime

        import numpy as np

        from pathway_tpu.engine import collective_exchange as _collective

        elided = self._elided
        for consumer, port in self.scopes[0].nodes[producer.index].consumers:
            if (producer.index, consumer.index, port) in elided:
                # optimizer-proven redundant exchange: every row already
                # lives on `worker` — skip the routing digests entirely
                if _VERIFY_ELISION:
                    _assert_colocated(consumer, port, out, worker, self.n)
                EXCHANGE_STATS["elided"] += 1
                EXCHANGE_STATS["repartitions"] += 1
                self.scopes[worker].nodes[consumer.index].push(port, out)
                continue
            fn = self._partition_fn(consumer, port)
            if fn is None:
                EXCHANGE_STATS["host_deliveries"] += 1
                EXCHANGE_STATS["repartitions"] += 1
                target = self.scopes[0].nodes[consumer.index]
                target.push(port, out)
                continue
            if out._entries is None and out.columns is not None:
                shards = self._columnar_shards(consumer, port, out)
                if shards is not None:
                    cparts = _collective.exchange(
                        consumer.index,
                        out.columns,
                        shards,
                        self.n,
                        consumer=consumer,
                    )
                    if cparts is not None:
                        EXCHANGE_STATS["collective_deliveries"] += 1
                        EXCHANGE_STATS["repartitions"] += 1
                        for w, cols in enumerate(cparts):
                            if cols is None:
                                continue
                            part = DeltaBatch.from_columns(
                                cols,
                                consolidated=out._consolidated,
                                insert_only=out._insert_only,
                            )
                            part._raw_insert_only = out._raw_insert_only
                            self.scopes[w].nodes[consumer.index].push(
                                port, part
                            )
                        continue
                    # host gather split — timed only while the per-edge
                    # exchange policy is comparing sides (one cached env
                    # check otherwise)
                    track = _collective.tracking(self.n)
                    t0 = _walltime.perf_counter_ns() if track else 0
                    EXCHANGE_STATS["host_deliveries"] += 1
                    EXCHANGE_STATS["repartitions"] += 1
                    for w in range(self.n):
                        idx = np.flatnonzero(shards == w)
                        if not len(idx):
                            continue
                        part = DeltaBatch.from_columns(
                            out.columns.gather(idx),
                            consolidated=out._consolidated,
                            insert_only=out._insert_only,
                        )
                        part._raw_insert_only = out._raw_insert_only
                        self.scopes[w].nodes[consumer.index].push(
                            port, part
                        )
                    if track:
                        _collective.record_host(
                            consumer.index,
                            out.columns.n,
                            _walltime.perf_counter_ns() - t0,
                        )
                    continue
            EXCHANGE_STATS["host_deliveries"] += 1
            EXCHANGE_STATS["repartitions"] += 1
            parts: list[list[Entry]] = [[] for _ in range(self.n)]
            shards = entry_shards(
                partition_rule(consumer, port), out.entries, self.n
            )
            if shards is not None:
                for e, w in zip(out.entries, shards):
                    parts[w].append(e)
            else:
                for key, row, diff in out:
                    parts[fn(key, row)].append((key, row, diff))
            for w, entries in enumerate(parts):
                if entries:
                    batch = DeltaBatch(entries)
                    batch._consolidated = out._consolidated
                    self.scopes[w].nodes[consumer.index].push(port, batch)

    @property
    def scope(self) -> Scope:
        """Canonical scope for monitoring (worker 0 carries the superset)."""
        return self.scopes[0]

    def _stats_of(self, node: Node):
        from pathway_tpu.engine.graph import OperatorStats

        st = self.stats.get(node.index)
        if st is None:
            with self._stats_lock:
                st = self.stats.setdefault(node.index, OperatorStats())
        return st

    def propagate(self, time: int) -> None:
        from pathway_tpu.internals import tracing as _tracing

        probe = self.probe
        trace = _tracing.current()
        if probe or trace is not None:
            import time as _walltime
        # traced runs attribute device-resident operator kernel time to
        # the span that launched it (critical-path analysis needs the
        # per-node split, not just the global kernel_ns bucket)
        _dops = None
        if trace is not None:
            from pathway_tpu.engine import device_ops as _device_ops

            if _device_ops.enabled():
                _dops = _device_ops
        while True:
            busy = False
            busy_nodes = 0
            for w, scope in enumerate(self.scopes):
                for node in scope.nodes:
                    if not node.has_pending():
                        continue
                    busy = True
                    busy_nodes += 1
                    if probe or trace is not None:
                        t0 = _walltime.perf_counter()
                    dns0 = _dops.total_ns() if _dops is not None else 0
                    out = node.process(time)
                    if out is None:
                        out = DeltaBatch()
                    # defer like the single scheduler: an eager apply
                    # would materialise columnar batches before the
                    # vectorized exchange can route them
                    node._defer_state(out)
                    if trace is not None:
                        extra = {}
                        if _dops is not None:
                            dns = _dops.total_ns() - dns0
                            if dns:
                                extra["device_ns"] = dns
                        trace.span(
                            getattr(node, "name", None)
                            or type(node).__name__,
                            "sink"
                            if isinstance(node, SubscribeNode)
                            else "op",
                            t0,
                            _walltime.perf_counter(),
                            node=node.index,
                            shard=w,
                            **extra,
                        )
                    if probe:
                        st = self._stats_of(node)
                        st.time_spent += _walltime.perf_counter() - t0
                        st.batches += 1
                        st.last_time = time
                        cols = out.columns
                        if cols is not None:
                            if cols.diffs is None:
                                st.insertions += cols.n
                            else:
                                pos = int((cols.diffs > 0).sum())
                                st.insertions += pos
                                st.deletions += cols.n - pos
                        else:
                            for _k, _r, d in out.consolidate():
                                if d > 0:
                                    st.insertions += 1
                                else:
                                    st.deletions += 1
                    if out:
                        self._deliver(w, node, out)
            if probe:
                self._queue_gauge.value = float(busy_nodes)
            if busy:
                continue
            flushed = False
            for scope in self.scopes:
                for node in scope.nodes:
                    if isinstance(node, ErrorLogNode):
                        batch = node.flush_buffer()
                        if batch:
                            node.push(0, batch)
                            flushed = True
            if not flushed:
                break
        for scope in self.scopes:
            for node in scope.nodes:
                node.on_time_end(time)
        from pathway_tpu.engine import device_pipeline

        device_pipeline.commit_boundary(time)

    def _analysis_intercept(self) -> bool:
        """Analyze-only mode: the workers are identical replicas, so the
        worker-0 scope (the superset — sinks attach there) is analyzed
        once and execution is skipped."""
        from pathway_tpu.analysis import runtime as _analysis_runtime

        return _analysis_runtime.intercept(self.scopes[0])

    def commit(self) -> int:
        if self._analysis_intercept():
            time = self.time
            self.time += 1
            return time
        for w, scope in enumerate(self.scopes):
            for node in scope.nodes:
                if isinstance(node, StaticSource):
                    # the same static rows exist on every worker replica;
                    # only worker 0 emits, the exchange spreads them
                    batch = node.initial_batch() if w == 0 else None
                    if w != 0:
                        node._emitted = True
                    if batch:
                        self._route_source(node, batch)
                elif isinstance(node, InputSession):
                    batch = node.flush()
                    if batch:
                        # flush may return raw diffs; routing applies state
                        self._route_source(node, batch.consolidate())
        time = self.time
        self.propagate(time)
        self.time += 1
        return time

    def _route_source(self, node: Node, batch: DeltaBatch) -> None:
        """Sources read whole on worker 0 and reshard at the exchange
        (reference dataflow.rs:3492).

        State bookkeeping serves two invariants at once:
        - the worker-0 replica keeps the FULL source state, so
          upsert/remove flushes resolve against complete history and emit
          retractions for rows whose shard lives elsewhere;
        - replicas w>0 keep their row-key shard, so consumers that peek at
          an input's ``current`` (zip/update/ix source side) find exactly
          the rows whose downstream parts they receive."""
        if batch._entries is not None and len(batch) >= VECTOR_THRESHOLD:
            # bulk source commits enter the exchange as arrays so the
            # replica sharding and consumer routes below run the
            # vectorized kernel, not a per-row hash loop (static sources
            # arrive raw — consolidate first, since the columnar twin
            # asserts unique-key +1 invariants)
            cbatch = columnarize_entries(batch.consolidate())
            if cbatch is not None:
                batch = cbatch
        replica0 = self.scopes[0].nodes[node.index]
        replica0._defer_state(batch)
        if self.n > 1:
            shards = None
            if batch._entries is None and batch.columns is not None:
                shards = columnar_shards(("key",), batch.columns, self.n)
            if shards is not None:
                import numpy as np

                for w in range(1, self.n):
                    idx = np.flatnonzero(shards == w)
                    if len(idx):
                        self.scopes[w].nodes[node.index]._defer_state(
                            DeltaBatch.from_columns(
                                batch.columns.gather(idx),
                                consolidated=batch._consolidated,
                            )
                        )
            else:
                parts: list[list[Entry]] = [[] for _ in range(self.n)]
                key_shards = shards_of_values(
                    [e[0] for e in batch.entries], self.n
                )
                for e, w in zip(batch.entries, key_shards):
                    parts[w].append(e)
                for w in range(1, self.n):
                    if parts[w]:
                        replica = self.scopes[w].nodes[node.index]
                        replica._defer_state(DeltaBatch(parts[w]))
        self._deliver(0, replica0, batch)

    def finish(self) -> None:
        if self._analysis_intercept():
            return
        self.commit()
        for scope in self.scopes:
            for node in scope.nodes:
                node.on_end()
        if any(
            n.has_pending() for s in self.scopes for n in s.nodes
        ):
            self.propagate(self.time)
            self.time += 1
        from pathway_tpu.engine import device_pipeline

        device_pipeline.drain()
        for scope in self.scopes:
            for node in scope.nodes:
                node.close()

    # -- results --------------------------------------------------------------

    def merged_state(self, index: int) -> dict[Pointer, tuple]:
        """Union of one operator's state across workers (for captures)."""
        out: dict[Pointer, tuple] = {}
        for scope in self.scopes:
            out.update(scope.nodes[index].current)
        return out
