"""Device-resident incremental operators: JAX kernels for the hottest
stateful dataflow ops (ROADMAP item 3).

Three operator cores move to the accelerator, each operating directly on
the columnar delta-batch arrays (+1/−1 diff semantics included):

- **groupby semigroup reduction** — the per-commit segment reductions of
  the columnar groupby state machine (``device.segment_count`` +
  ``device.segment_sum``) become one batch of device scatter-adds over
  the factorized key ``inverse``.  Dispatch is split from fetch
  (:func:`segment_reduce_dispatch` → :meth:`SegmentReduceJob.fetch`) so
  the kernel launch overlaps the host group-id resolution loop — the
  same overlap discipline as the PR-9 async device pipeline.
- **hash-join probe** — the sort-based pair matcher
  (``graph._match_join_pairs``) re-expressed over int64 key digests on
  device (:func:`match_pairs`): stable argsort + searchsorted +
  vectorized expansion.  The swap rule (smaller side becomes the sorted
  haystack) and the emission order (probe index ascending, build index
  ascending within a probe row) are copied verbatim, so the device
  matcher is interchangeable with the host matcher *pair for pair* —
  ordering depends only on side lengths and key-equality structure,
  never on code values.
- **KNN index maintenance** — ops/knn.py's scatter update and masked
  matmul top-k already run on device; this module adds the accounting
  seam (:func:`record_kernel`) so their launches land in the same
  ``hit_counts``/``kernel_ns`` surface as the C++ host kernels, and
  :class:`~pathway_tpu.engine.external_index.HostKnnIndex` becomes their
  bit-exact host spec.

Bit-exactness discipline (PR 2): the host NumPy/C++ kernels remain the
spec.  The device kernels only *reorder additions* (scatter-add) or
*reproduce a deterministic algorithm* (stable sort matcher) — the
multiply producing the weights happens on host with NumPy so its
rounding is the spec's rounding by construction, and padding rows
contribute exact zeros (a group sum can never be ``-0.0``: the host
accumulator starts at ``+0.0`` and ``+0.0 + -0.0 == +0.0``).  The
parity gate in tools/check.py re-runs the corpus with the JAX path
forced on, per platform.

Placement is measurement-driven, not static: the optimizer's placement
pass (:mod:`pathway_tpu.optimize.placement`) seeds a per-operator
policy that compares observed device ns/row against host ns/row with
hysteresis.  ``PATHWAY_TPU_DEVICE_OPS`` is the control surface:

- ``0`` — escape hatch, host kernels only (bit-identical, zero new code
  on the hot path);
- ``1`` — force the device path wherever the batch is representable
  (CI uses this under ``JAX_PLATFORMS=cpu`` to exercise the JAX
  kernels without an accelerator);
- unset — auto: device ops engage only when jax is already loaded *and*
  the default backend is a real accelerator; pure-host deployments pay
  one cached env check per batch and nothing else.
"""

from __future__ import annotations

import os
import sys
import threading
import time as _time
from typing import Any, Sequence

import numpy as np

__all__ = [
    "bucket_size",
    "enabled",
    "forced",
    "hit_counts",
    "kernel_ns",
    "record_kernel",
    "reset_counters",
    "segment_reduce_dispatch",
    "SegmentReduceJob",
    "match_pairs",
    "stats",
]

_LOCK = threading.Lock()
#: per-kernel launch counts / host-observed ns, mirroring native.hit_counts()
_HITS: dict[str, int] = {}
_NS: dict[str, int] = {}

_JAX_OK: bool | None = None
_BACKEND: str | None | bool = False  # False = not probed yet
_ENABLED_CACHE: tuple[str, bool] | None = None
_SCATTER_ADD = None


def _jax_ok() -> bool:
    """jax importable (cached) — never raises."""
    global _JAX_OK
    if _JAX_OK is None:
        try:
            import jax  # noqa: F401
            import jax.numpy  # noqa: F401

            _JAX_OK = True
        except Exception:
            _JAX_OK = False
    return _JAX_OK


def _default_backend() -> str | None:
    global _BACKEND
    if _BACKEND is False:
        try:
            import jax

            _BACKEND = jax.default_backend()
        except Exception:
            _BACKEND = None
    return _BACKEND


def enabled() -> bool:
    """Whether device ops may engage at all (see the env contract above).

    Cached per env value: the scheduler hot path calls this once per
    batch, so the auto probe (backend detection) runs at most once."""
    global _ENABLED_CACHE
    raw = os.environ.get("PATHWAY_TPU_DEVICE_OPS", "").strip().lower()
    cached = _ENABLED_CACHE
    if cached is not None and cached[0] == raw:
        return cached[1]
    if raw in ("0", "false", "off", "no"):
        val = False
    elif raw in ("1", "true", "on", "yes", "force"):
        val = _jax_ok()
    else:
        # auto: only with jax already resident AND a real accelerator —
        # never silently re-route host CPU work through jax-on-CPU
        val = (
            "jax" in sys.modules
            and _jax_ok()
            and _default_backend() not in (None, "cpu")
        )
    _ENABLED_CACHE = (raw, val)
    return val


def forced() -> bool:
    """True when ``PATHWAY_TPU_DEVICE_OPS=1`` pins placement to device
    (parity CI); the policy then skips measurement-driven arbitration."""
    raw = os.environ.get("PATHWAY_TPU_DEVICE_OPS", "").strip().lower()
    return raw in ("1", "true", "on", "yes", "force") and enabled()


# -- accounting (the native.hit_counts()/kernel_ns() twin) --------------------


def record_kernel(name: str, ns: int, hits: int = 1) -> None:
    with _LOCK:
        _HITS[name] = _HITS.get(name, 0) + hits
        _NS[name] = _NS.get(name, 0) + int(ns)


def hit_counts() -> dict[str, int]:
    with _LOCK:
        return dict(_HITS)


def kernel_ns() -> dict[str, int]:
    with _LOCK:
        return dict(_NS)


def total_ns() -> int:
    """Cumulative device-kernel ns across every kernel — cheap enough to
    sample around a single operator batch (span attribution)."""
    with _LOCK:
        return sum(_NS.values())


def reset_counters() -> None:
    with _LOCK:
        _HITS.clear()
        _NS.clear()


def stats() -> dict:
    """Structured roll-up for bench JSON / cli stats."""
    from pathway_tpu.optimize import placement as _placement

    return {
        "enabled": enabled(),
        "forced": forced(),
        "hit_counts": hit_counts(),
        "kernel_ns": kernel_ns(),
        "placement": _placement.POLICY.decisions(),
    }


# -- shared kernel plumbing ---------------------------------------------------


def bucket_size(n: int, minimum: int = 8) -> int:
    """Power-of-two padding bucket — ragged batch lengths otherwise
    compile one XLA program per distinct shape (the Ragged Paged
    Attention discipline: pad irregular segments to few static shapes).
    Public: the collective exchange pads its chunk/bucket depths through
    the same ladder so both planes share compiled-shape discipline."""
    b = minimum
    while b < n:
        b *= 2
    return b


#: historical internal alias
_bucket = bucket_size


def _scatter_add():
    """The one jitted kernel shape every segment reduction uses:
    ``out0.at[inv].add(w)`` with the (freshly zeroed) output donated.
    jax caches compilations per (dtype, bucketed shape) pair."""
    global _SCATTER_ADD
    if _SCATTER_ADD is None:
        import jax

        _SCATTER_ADD = jax.jit(
            lambda out0, inv, w: out0.at[inv].add(w), donate_argnums=(0,)
        )
    return _SCATTER_ADD


# -- groupby: segment reduction ----------------------------------------------


class SegmentReduceJob:
    """An in-flight device segment reduction: :func:`segment_reduce_dispatch`
    launched the scatter-adds (jax async dispatch — the call returned as
    soon as the work was enqueued); :meth:`fetch` materialises the host
    arrays, blocking only on actual device completion.  The caller runs
    its host-side group-id resolution between the two."""

    __slots__ = ("_gd", "_outs", "_nu", "_n", "_t0")

    def __init__(self, gd, outs, nu: int, n: int, t0: int) -> None:
        self._gd = gd
        self._outs = outs
        self._nu = nu
        self._n = n
        self._t0 = t0

    def fetch(self) -> tuple[np.ndarray, list]:
        """(gdiffs, deltas) with the padding sliced off — dtypes and
        values bit-identical to device.segment_count/segment_sum."""
        from pathway_tpu.engine import device_residency as _dres

        nu = self._nu
        full = np.asarray(self._gd)
        d2h = full.nbytes
        gdiffs = full[:nu]
        deltas = []
        for o in self._outs:
            if o is None:
                deltas.append(None)
                continue
            arr = np.asarray(o)
            d2h += arr.nbytes
            deltas.append(arr[:nu])
        _dres.record_d2h(d2h)
        record_kernel(
            "segment_reduce", _time.perf_counter_ns() - self._t0
        )
        return gdiffs, deltas


def segment_reduce_dispatch(
    inverse: np.ndarray,
    diffs: np.ndarray,
    vals: Sequence[np.ndarray | None],
    n_groups: int,
) -> SegmentReduceJob:
    """Device twin of the columnar groupby's per-commit reductions:
    ``segment_count(inverse, diffs)`` plus one ``segment_sum`` per sum
    column, as a single batch of bucketed scatter-adds.

    The weight products (``values.astype(int64) * diffs`` wrapping int64,
    ``values * diffs`` float64) are computed on host with NumPy — the
    device only reorders the additions, which is exact for ints and holds
    bit-for-bit for floats on every platform the parity gate has run on
    (XLA's scatter-add ordering is validated, not assumed)."""
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    from pathway_tpu.engine import device_residency as _dres

    t0 = _time.perf_counter_ns()
    n = len(inverse)
    npad = _bucket(n)
    gpad = _bucket(n_groups)
    inv = np.zeros(npad, np.int64)
    inv[:n] = inverse
    h2d = inv.nbytes
    with enable_x64():
        add = _scatter_add()
        inv_d = jnp.asarray(inv)
        w = np.zeros(npad, np.int64)
        w[:n] = diffs
        h2d += w.nbytes
        gd = add(jnp.zeros(gpad, jnp.int64), inv_d, jnp.asarray(w))
        outs: list[Any] = []
        for col in vals:
            if col is None:
                outs.append(None)
                continue
            if col.dtype.kind in "ib":
                w = np.zeros(npad, np.int64)
                w[:n] = col.astype(np.int64, copy=False) * diffs
                h2d += w.nbytes
                outs.append(
                    add(jnp.zeros(gpad, jnp.int64), inv_d, jnp.asarray(w))
                )
            else:
                w = np.zeros(npad, np.float64)
                w[:n] = col * diffs
                h2d += w.nbytes
                outs.append(
                    add(
                        jnp.zeros(gpad, jnp.float64), inv_d, jnp.asarray(w)
                    )
                )
    _dres.record_h2d(h2d)
    return SegmentReduceJob(gd, outs, n_groups, n, t0)


# -- join: sort-based pair matcher -------------------------------------------


def _match_pairs_device(
    la: np.ndarray, ra: np.ndarray, la_dev=None, ra_dev=None
):
    """graph._match_join_pairs transliterated to jnp — identical swap
    rule, stable sort, and emission arithmetic, so the returned pair
    sequence is the host matcher's pair sequence.

    ``la_dev``/``ra_dev`` are optional device twins of the SAME code
    arrays (a still-resident exchange delivery's int64 key column):
    when present the matcher consumes them in place of re-uploading the
    host array — values identical by construction (both views
    reinterpret the same wire bytes), so pair output cannot differ."""
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    from pathway_tpu.engine import device_residency as _dres

    empty = np.empty(0, np.int64)
    if len(la) == 0 or len(ra) == 0:
        return empty, empty
    if len(ra) > len(la):
        r_idx, l_idx = _match_pairs_device(ra, la, ra_dev, la_dev)
        return l_idx, r_idx
    with enable_x64():
        if la_dev is not None:
            la_d = la_dev
            _dres.record_saved(la.nbytes)
            _dres.RESIDENCY_STATS["device_consumes"] += 1
        else:
            la_d = jnp.asarray(la)
            _dres.record_h2d(la.nbytes)
        if ra_dev is not None:
            ra_d = ra_dev
            _dres.record_saved(ra.nbytes)
            _dres.RESIDENCY_STATS["device_consumes"] += 1
        else:
            ra_d = jnp.asarray(ra)
            _dres.record_h2d(ra.nbytes)
        order = jnp.argsort(ra_d, stable=True)
        rs = ra_d[order]
        lo = jnp.searchsorted(rs, la_d, side="left")
        hi = jnp.searchsorted(rs, la_d, side="right")
        counts = hi - lo
        total = int(counts.sum())
        if total == 0:
            return empty, empty
        l_idx = jnp.repeat(jnp.arange(len(la)), counts)
        starts = jnp.repeat(lo, counts)
        csum = jnp.cumsum(counts) - counts
        offs = jnp.arange(total) - jnp.repeat(csum, counts)
        r_idx = order[starts + offs]
        l_out = np.asarray(l_idx, np.int64)
        r_out = np.asarray(r_idx, np.int64)
        _dres.record_d2h(l_out.nbytes + r_out.nbytes)
        return l_out, r_out


def match_pairs(
    l_arrays: "list[np.ndarray]",
    r_arrays: "list[np.ndarray]",
    l_dev=None,
    r_dev=None,
):
    """Device pair matcher over dtype-unified join-key columns; returns
    ``(l_idx, r_idx)`` or ``None`` when a column has no int64 code view
    (caller falls back to the host matcher — state untouched).

    Multi-column keys reduce to joint codes with the same host
    factorization the NumPy path uses; only the matcher itself (the
    sort/search dominated part) runs on device, so pair ordering is the
    host ordering by construction.

    ``l_dev``/``r_dev``: optional device twins of single-column keys (a
    device-resident exchange delivery).  A twin is consumed ONLY when
    the int64 code derivation was the identity on the host array it
    twins (``_as_match_codes`` returns the same object for contiguous
    int64 input) — float normalisation or widening would change bits,
    so any non-identity derivation drops the twin and re-uploads."""
    from pathway_tpu.engine.graph import _as_match_codes

    t0 = _time.perf_counter_ns()
    lc = [_as_match_codes(a) for a in l_arrays]
    if any(c is None for c in lc):
        return None
    rc = [_as_match_codes(a) for a in r_arrays]
    if any(c is None for c in rc):
        return None
    la_dev = ra_dev = None
    if len(lc) == 1:
        la, ra = lc[0], rc[0]
        if l_dev is not None and lc[0] is l_arrays[0]:
            la_dev = l_dev
        if r_dev is not None and rc[0] is r_arrays[0]:
            ra_dev = r_dev
    else:
        from pathway_tpu.engine.device import factorize_multi

        nl = len(lc[0])
        both = [np.concatenate([l, r]) for l, r in zip(lc, rc)]
        _first, inverse = factorize_multi(both)
        la, ra = inverse[:nl], inverse[nl:]
    out = _match_pairs_device(la, ra, la_dev, ra_dev)
    record_kernel("match_pairs", _time.perf_counter_ns() - t0)
    return out
