"""Engine graph: operator nodes, the Scope API, and the commit scheduler.

This is the TPU-native replacement for the reference's Rust engine
(reference: `Graph` trait src/engine/graph.rs:643-990 implemented by
`DataflowGraphInner` src/engine/dataflow.rs:820 over timely/differential).
Instead of translating timely, we keep the *contract* — tables are keyed
update streams processed per commit timestamp — and execute with a host-side
topological scheduler: every operator consumes consolidated delta batches at
time ``t`` and emits output deltas at ``t``. Heavy math (UDF microbatches,
vector search) is dispatched to JAX/XLA on TPU by the device-side operators;
everything here is control plane.

Key design points vs the reference:
- Differential's bilinear join update is realized per affected join-key group
  (recompute local old/new output, emit the difference) — same output stream,
  simpler state machine.
- Retraction of nondeterministic expression outputs reuses the operator's own
  current-state map, so deletions always cancel prior insertions (the
  reference needs a dedicated MapWithConsistentDeletions wrapper,
  src/engine/dataflow/operators.rs:308).
"""

from __future__ import annotations

import itertools
import time as _time
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from pathway_tpu.engine.batch import DeltaBatch, apply_batch_to_state
from pathway_tpu.engine.device import VECTOR_THRESHOLD
from pathway_tpu.engine.expression import EngineExpression, EvalContext
from pathway_tpu.engine.reducers import Reducer
from pathway_tpu.engine.value import ERROR, Error, Pointer, hash_values, is_error, ref_scalar, rows_differ
from pathway_tpu.internals import metrics as _metrics
from pathway_tpu.internals import tracing as _tracing

#: sink-side row counter; one shared series — the per-commit delta is what
#: stamps the ingest->sink latency histogram (internals/runner.py)
_OUTPUT_ROWS = _metrics.REGISTRY.counter(
    "pathway_output_rows_total",
    "rows delivered to subscribe sinks (insertions and retractions)",
)


class Node:
    """An operator in the engine graph."""

    def __init__(self, scope: "Scope", inputs: Sequence["Node"], arity: int) -> None:
        self.scope = scope
        self.inputs = list(inputs)
        self.arity = arity
        self.index = len(scope.nodes)
        scope.nodes.append(self)
        self.consumers: list[tuple[Node, int]] = []
        self.pending: dict[int, list[DeltaBatch]] = {}
        self._state: dict[Pointer, tuple] = {}
        self._state_lag: list[DeltaBatch] = []
        self._state_lag_rows = 0
        self.name: str = type(self).__name__
        self.trace: Any = None
        for port, inp in enumerate(self.inputs):
            inp.consumers.append((self, port))

    # -- lazy state ---------------------------------------------------------
    #
    # A node's ``current`` (key -> row) is only needed when somebody
    # actually observes it: a retraction arriving at this operator, a
    # state-peeking consumer (zip/ix/update/restrict), a snapshot, a test.
    # Differential dataflow pays for arrangements only where they exist;
    # here output batches are deferred and applied on first read, so a
    # bulk pipeline whose state is never inspected materialises no
    # per-row dict entries at all. Deferred columnar batches are cheap
    # (arrays); deferred row batches hold live tuples either way. The
    # rows cap bounds memory for long streams whose state nobody reads.

    _STATE_LAG_MAX_ROWS = 1 << 21

    @property
    def current(self) -> dict[Pointer, tuple]:
        if self._state_lag:
            lag, self._state_lag = self._state_lag, []
            self._state_lag_rows = 0
            for batch in lag:
                # deferred batches may be raw (the scheduler no longer
                # pre-consolidates); state application needs merged diffs
                apply_batch_to_state(self._state, batch.consolidate())
        return self._state

    @current.setter
    def current(self, value: dict[Pointer, tuple]) -> None:
        self._state = value
        self._state_lag = []
        self._state_lag_rows = 0

    def _defer_state(self, batch: DeltaBatch) -> None:
        """Queue an output batch for lazy application to ``current``."""
        self._state_lag.append(batch)
        self._state_lag_rows += len(batch)
        if self._state_lag_rows > self._STATE_LAG_MAX_ROWS:
            self.current  # noqa: B018 — drain via the property

    # -- scheduler interface ------------------------------------------------

    def has_pending(self) -> bool:
        return bool(self.pending)

    def take(self, port: int) -> DeltaBatch:
        return self.take_raw(port).consolidate()

    def take_raw(self, port: int) -> DeltaBatch:
        """Like :meth:`take` but without consolidation — for diff-linear
        consumers (segment-sum groupby) that tolerate duplicate and
        net-zero (key, row) entries."""
        batches = self.pending.pop(port, None)
        if not batches:
            return DeltaBatch()
        if len(batches) == 1:
            return batches[0]
        if all(b._entries is None for b in batches):
            # stay columnar: concatenating arrays keeps the zero-PyObject
            # path intact for the downstream segment consumer
            from pathway_tpu.engine.batch import Columns

            stacked = Columns.concat([b.columns for b in batches])
            if stacked is not None:
                out = DeltaBatch.from_columns(stacked, consolidated=False)
                # all-+1 inputs stay all-+1 stacked (keys may repeat
                # across parts, so the consolidated insert_only flag —
                # which asserts uniqueness — must NOT propagate)
                out._raw_insert_only = all(
                    b._raw_insert_only for b in batches
                )
                return out
        merged = DeltaBatch()
        for b in batches:
            merged.extend(b)
        return merged

    def push(self, port: int, batch: DeltaBatch) -> None:
        if batch:
            self.pending.setdefault(port, []).append(batch)

    def process(self, time: int) -> DeltaBatch:
        raise NotImplementedError

    def on_time_end(self, time: int) -> None:
        pass

    def on_end(self) -> None:
        pass

    def close(self) -> None:
        """Final resource teardown, after the post-``on_end`` settlement
        commit — ``on_end`` may inject final batches (temporal buffer
        flush) that still have to reach sinks, so sinks must not close
        inside ``on_end`` itself."""

    def report(self, key: Pointer | None, message: str) -> None:
        self.scope.report_error(self, key, message)

    def snapshot(self) -> dict[Pointer, tuple]:
        return dict(self.current)

    # -- operator persistence (reference: operator_snapshot.rs) --------------

    #: mutable attributes beyond ``current`` that define operator state;
    #: captured at commit boundaries by OperatorSnapshotManager
    STATE_ATTRS: tuple = ()

    def op_state(self) -> dict:
        state: dict = {"current": dict(self.current)}
        for name in self.STATE_ATTRS:
            state[name] = getattr(self, name)
        return state

    def restore_op_state(self, state: dict) -> None:
        self.current = dict(state["current"])
        for name in self.STATE_ATTRS:
            if name in state:
                setattr(self, name, state[name])


class StaticSource(Node):
    """A table fully known at graph build time."""

    #: restored snapshots already contain these rows — a resumed run must
    #: not re-emit them (operator persistence)
    STATE_ATTRS = ("_emitted",)

    def __init__(self, scope: "Scope", rows: Iterable[tuple[Pointer, tuple]], arity: int):
        super().__init__(scope, [], arity)
        self._rows = list(rows)
        self._emitted = False

    def initial_batch(self) -> DeltaBatch | None:
        if self._emitted:
            return None
        self._emitted = True
        out = DeltaBatch((k, r, 1) for k, r in self._rows)
        out._raw_insert_only = True  # diffs literally +1 by construction
        return out

    def process(self, time: int) -> DeltaBatch:
        return self.take_raw(0)  # pass-through; consumers consolidate


class InputSession(Node):
    """Mutable input: connectors push inserts/removes/upserts, then commit.

    Mirrors the reference's InputSession / UpsertSession pair
    (src/connectors/adaptors.rs:23-60): in upsert mode an insert for an
    existing key retracts the previous row first.
    """

    def __init__(self, scope: "Scope", arity: int, upsert: bool = False):
        super().__init__(scope, [], arity)
        self.upsert = upsert
        self._buffer: list[tuple[Pointer, tuple | None, int]] = []
        self._has_removals = False
        self._has_rowless_removals = False

    def insert(self, key: Pointer, row: tuple) -> None:
        self._buffer.append((key, row, 1))

    def remove(self, key: Pointer, row: tuple | None = None) -> None:
        self._buffer.append((key, row, -1))
        self._has_removals = True
        if row is None:
            self._has_rowless_removals = True

    def flush(self) -> DeltaBatch | None:
        if not self._buffer:
            return None
        if not self.upsert and not self._has_rowless_removals:
            # dominant connector shapes: plain inserts, or removals that
            # carry their row — neither needs the per-row overlay (the
            # overlay exists solely to resolve row-less removals against
            # this commit's earlier updates and prior state)
            out = DeltaBatch(self._buffer)
            self._buffer = []
            if not self._has_removals:
                # every diff is +1 by construction of insert(); multiset-
                # correct consumers (columnar join) key off this hint and
                # dict-state consumers still consolidate in take()
                out._raw_insert_only = True
            self._has_removals = False
            return out
        state = self.current  # hoisted: property drains lazily-applied state
        from pathway_tpu.native import kernels as _native

        if (
            _native is not None
            and hasattr(_native, "session_overlay")
            and type(state) is dict
        ):
            # the whole overlay resolution (upsert retractions, row-less
            # removals against this commit's earlier updates) in one call
            entries = _native.session_overlay(
                self._buffer, state, self.upsert
            )
            if entries is not None:
                self._buffer.clear()
                self._has_removals = False
                self._has_rowless_removals = False
                return DeltaBatch(entries).consolidate()
        out = DeltaBatch()
        # overlay of keys touched this commit: key -> row | None (absent row)
        overlay: dict[Pointer, tuple | None] = {}

        def effective(key: Pointer) -> tuple | None:
            if key in overlay:
                return overlay[key]
            return state.get(key)

        if self.upsert:
            for key, row, diff in self._buffer:
                prev = effective(key)
                if diff > 0:
                    if prev is not None:
                        out.append(key, prev, -1)
                    assert row is not None
                    out.append(key, row, 1)
                    overlay[key] = row
                else:
                    if prev is not None:
                        out.append(key, prev, -1)
                        overlay[key] = None
        else:
            for key, row, diff in self._buffer:
                if diff < 0 and row is None:
                    row = effective(key)
                    if row is None:
                        continue
                if diff > 0:
                    overlay[key] = row
                elif effective(key) == row:
                    overlay[key] = None
                out.append(key, row, diff)  # type: ignore[arg-type]
        self._buffer.clear()
        self._has_removals = False
        self._has_rowless_removals = False
        return out.consolidate()

    def process(self, time: int) -> DeltaBatch:
        # pure pass-through: keep the batch raw so diff-linear consumers
        # (columnar groupby) can skip consolidation entirely
        return self.take_raw(0)


class ExpressionNode(Node):
    """Per-row expression evaluation (select/with_columns/apply).

    Deletions are retracted from ``current`` rather than re-evaluated, which
    keeps nondeterministic UDF outputs consistent between insert and delete.
    """

    def __init__(
        self,
        scope: "Scope",
        source: Node,
        expressions: Sequence[EngineExpression],
    ) -> None:
        super().__init__(scope, [source], len(expressions))
        self.expressions = list(expressions)

    def _columnar_inserts(self, batch: DeltaBatch) -> DeltaBatch | None:
        """Pure-insert batch → columnar output sharing the input's keys;
        None falls back to the row/entry paths."""
        from pathway_tpu.engine import device
        from pathway_tpu.engine.batch import Columns
        from pathway_tpu.native import kernels as _native

        payload = batch.columns
        if payload is not None:
            view: Any = device.PayloadView(payload)
        else:
            view = device.ColumnarView(batch.entries, from_entries=True)
        arrays = []
        for expr in self.expressions:
            try:
                arrays.append(device.eval_columnar(expr, view))
            except device.NotVectorizable:
                return None
        if payload is not None:
            out_payload = Columns.with_keys_of(payload, arrays)
        else:
            entries = batch.entries
            if _native is not None:
                kb = _native.entry_keys_bytes(entries, Pointer)
            else:
                kb = _entry_keys_bytes_py(entries)
            if kb is None:
                return None  # non-Pointer keys: row path
            out_payload = Columns(len(entries), arrays, kbytes=kb)
        out = DeltaBatch.from_columns(
            out_payload,
            consolidated=batch._insert_only,
            insert_only=batch._insert_only,
        )
        # keys are the input's: its all-+1 hint carries over verbatim
        out._raw_insert_only = batch._raw_insert_only or out._insert_only
        return out

    def process(self, time: int) -> DeltaBatch:
        batch = self.take_raw(0)
        if not (batch._insert_only or batch._raw_insert_only):
            batch = batch.consolidate()
        insert_only = batch._insert_only or batch._raw_insert_only
        if insert_only and len(batch) >= VECTOR_THRESHOLD:
            fast = self._columnar_inserts(batch)
            if fast is not None:
                return fast
        out = DeltaBatch()
        ctx = EvalContext()
        if not insert_only:
            state = self.current  # hoisted: drains lazy state once
            for key, row, diff in batch:
                if diff < 0:
                    prev = state.get(key)
                    if prev is not None:
                        out.append(key, prev, diff)
        inserts = (
            batch.entries
            if insert_only
            else [e for e in batch if e[2] > 0]
        )
        if len(inserts) >= VECTOR_THRESHOLD:
            # columnar eval with row-materialised output (retraction case
            # or non-Pointer keys); falls back row-wise on mixed columns
            from pathway_tpu.engine.device import (
                eval_expressions_columnar_cols,
            )
            from pathway_tpu.native import kernels as _native

            cols = eval_expressions_columnar_cols(
                self.expressions, inserts, from_entries=True
            )
            if cols is not None:
                fresh = not out.entries
                if _native is not None:
                    out.entries.extend(_native.build_entries(inserts, cols))
                elif not cols:  # arity-0 select: one () row per key
                    out.entries.extend(
                        (key, (), diff) for key, _row, diff in inserts
                    )
                else:
                    out.entries.extend(
                        (key, new_row, diff)
                        for (key, _row, diff), new_row in zip(
                            inserts, zip(*cols)
                        )
                    )
                if fresh and batch._insert_only:
                    out._consolidated = True
                    out._insert_only = True
                return out
        for key, row, diff in inserts:
            new_row = tuple(expr.evaluate(key, row, ctx) for expr in self.expressions)
            out.append(key, new_row, diff)
        for key, message in ctx.errors:
            self.report(key, message)
        return out


class BatchApplyNode(Node):
    """Batched UDF execution over the arg-prep table (arity 1 output).

    The engine-side analog of the reference's async row map
    (map_named_async / MapWithConsistentDeletions,
    src/engine/dataflow/operators.rs:182,308): all rows inserted in a commit
    are handed to ``rows_fn`` at once — the executor decides concurrency
    (async) or fusion into one jit call (device microbatch). Deletions
    retract the memoized current value, so nondeterministic UDF outputs
    always cancel correctly.
    """

    def __init__(
        self,
        scope: "Scope",
        source: Node,
        rows_fn: Callable[[list], list],
        arg_cols: Sequence[int],
        propagate_none: bool = False,
    ) -> None:
        super().__init__(scope, [source], 1)
        self.rows_fn = rows_fn
        self.arg_cols = list(arg_cols)
        self.propagate_none = propagate_none

    def process(self, time: int) -> DeltaBatch:
        batch = self.take(0)
        out = DeltaBatch()
        state = self.current  # hoisted: drains lazy state once
        for key, row, diff in batch:
            if diff < 0:
                prev = state.get(key)
                if prev is not None:
                    out.append(key, prev, diff)
        pending: list[tuple[Pointer, tuple, int]] = []
        for key, row, diff in batch:
            if diff <= 0:
                continue
            args = tuple(row[c] for c in self.arg_cols)
            if any(is_error(a) for a in args):
                self.report(key, "error value in UDF argument")
                out.append(key, (ERROR,), diff)
                continue
            if self.propagate_none and any(a is None for a in args):
                out.append(key, (None,), diff)
                continue
            pending.append((key, args, diff))
        if pending:
            try:
                results = self.rows_fn([args for _k, args, _d in pending])
            except Exception as e:  # noqa: BLE001 — whole-batch failure
                results = [(False, e)] * len(pending)
            for (key, _args, diff), (ok, value) in zip(pending, results):
                if ok:
                    out.append(key, (value,), diff)
                else:
                    self.report(key, f"UDF error: {value!r}")
                    out.append(key, (ERROR,), diff)
        return out


class FilterNode(Node):
    def __init__(self, scope: "Scope", source: Node, condition_col: int) -> None:
        super().__init__(scope, [source], source.arity)
        self.condition_col = condition_col

    def process(self, time: int) -> DeltaBatch:
        batch = self.take_raw(0)
        if not (batch._insert_only or batch._raw_insert_only):
            batch = batch.consolidate()
        c = self.condition_col
        if batch._insert_only or batch._raw_insert_only:
            payload = batch.columns
            if payload is not None:
                cond = payload.cols[c]
                if cond.dtype.kind == "b":
                    # columnar mask-compress: keys/cols stay arrays
                    out = DeltaBatch.from_columns(
                        payload.compress(cond),
                        consolidated=batch._insert_only,
                        insert_only=batch._insert_only,
                    )
                    out._raw_insert_only = (
                        batch._raw_insert_only or out._insert_only
                    )
                    return out
            from pathway_tpu.native import kernels as _native

            if _native is not None:
                kept = _native.filter_truthy(batch.entries, c)
                if kept is not None:  # all-bool conditions, no errors
                    out = DeltaBatch()
                    out.entries = kept
                    out._consolidated = batch._insert_only
                    out._insert_only = batch._insert_only
                    out._raw_insert_only = True
                    return out
            if not any(is_error(e[1][c]) for e in batch.entries):
                # C-speed comprehension: no retractions, no error conditions
                out = DeltaBatch()
                out.entries = [e for e in batch.entries if e[1][c]]
                out._consolidated = batch._insert_only
                out._insert_only = batch._insert_only
                out._raw_insert_only = True
                return out
            batch = batch.consolidate()  # ERROR rows: exact row semantics
        out = DeltaBatch()
        state = self.current  # hoisted: drains lazy state once
        for key, row, diff in batch:
            if diff < 0:
                if key in state:
                    out.append(key, state[key], diff)
                continue
            cond = row[self.condition_col]
            if is_error(cond):
                self.report(key, "error value in filter condition")
                continue
            if cond:
                out.append(key, row, diff)
        return out


class ConcatNode(Node):
    """Disjoint union of universes (reference: concat_tables)."""

    def __init__(self, scope: "Scope", sources: Sequence[Node]) -> None:
        arity = sources[0].arity
        assert all(s.arity == arity for s in sources)
        super().__init__(scope, list(sources), arity)

    def _columnar_bulk(self, batches: list[DeltaBatch]) -> DeltaBatch | None:
        """Cold-state pure-insert concat: stack the columnar payloads and
        screen cross-input key uniqueness vectorized — the bulk-load path
        with zero per-row objects. None falls back to the row loop."""
        from pathway_tpu.engine.batch import Columns

        if self._state or self._state_lag:
            return None  # membership checks against prior keys: row path
        payloads = []
        for b in batches:
            if not b:
                continue
            if b.columns is None or not (
                b._insert_only or b._raw_insert_only
            ):
                return None
            payloads.append(b.columns)
        if not payloads:
            return DeltaBatch()
        stacked = (
            payloads[0] if len(payloads) == 1 else Columns.concat(payloads)
        )
        if stacked is None or stacked.diffs is not None:
            return None
        try:
            kb = stacked.kbytes()
        except (OverflowError, TypeError):
            return None
        if kb is None or not _keys_unique(
            np.ascontiguousarray(kb), stacked.n
        ):
            return None  # duplicate keys need the reporting row path
        out = DeltaBatch.from_columns(
            stacked, consolidated=True, insert_only=True
        )
        return out

    def process(self, time: int) -> DeltaBatch:
        batches = [
            self.take_raw(port) for port in range(len(self.inputs))
        ]
        fast = self._columnar_bulk(batches)
        if fast is not None:
            return fast
        out = DeltaBatch()
        seen = set(self.current)
        for batch in batches:
            for key, row, diff in batch.consolidate():
                if diff > 0:
                    if key in seen:
                        self.report(key, "duplicate key in concat")
                        continue
                    seen.add(key)
                else:
                    seen.discard(key)
                out.append(key, row, diff)
        return out.consolidate()


class ReindexNode(Node):
    """Re-key a table by a pointer column (reindex / with_id / with_id_from)."""

    def __init__(self, scope: "Scope", source: Node, key_col: int) -> None:
        super().__init__(scope, [source], source.arity)
        self.key_col = key_col

    def process(self, time: int) -> DeltaBatch:
        batch = self.take(0)
        out = DeltaBatch()
        for key, row, diff in batch:
            new_key = row[self.key_col]
            if is_error(new_key) or not isinstance(new_key, Pointer):
                self.report(key, f"reindex id must be a pointer, got {new_key!r}")
                continue
            out.append(new_key, row, diff)
        return out.consolidate()


class KeyFilterNode(Node):
    """intersect / subtract / restrict — filter rows by other tables' key sets."""

    def __init__(
        self, scope: "Scope", source: Node, others: Sequence[Node], mode: str
    ) -> None:
        super().__init__(scope, [source, *others], source.arity)
        assert mode in ("intersect", "subtract", "restrict")
        self.mode = mode

    def _member_in(self, key: Pointer, other_states: list[dict]) -> bool:
        if self.mode == "subtract":
            return not any(key in s for s in other_states)
        return all(key in s for s in other_states)

    def process(self, time: int) -> DeltaBatch:
        source = self.inputs[0]
        src_batch = self.take(0)
        # membership deltas from the other sides
        affected: set[Pointer] = set()
        for port in range(1, len(self.inputs)):
            for key, _row, _diff in self.take(port):
                affected.add(key)
        out = DeltaBatch()
        handled: set[Pointer] = set()
        for key, row, diff in src_batch:
            handled.add(key)
        # hoisted property reads: drain each lazy state once, not per row
        state = self.current
        others = [o.current for o in self.inputs[1:]]
        src_state = source.current if affected else None
        # keys whose membership may flip (and are not already being updated)
        for key in affected - handled:
            row = src_state.get(key)
            was = key in state
            now = row is not None and self._member_in(key, others)
            if was and not now:
                out.append(key, state[key], -1)
            elif not was and now and row is not None:
                out.append(key, row, 1)
        for key, row, diff in src_batch:
            if diff < 0:
                if key in state:
                    out.append(key, state[key], -1)
            else:
                if self._member_in(key, others):
                    out.append(key, row, 1)
        return out.consolidate()


class OverrideUniverseNode(Node):
    """Pass-through after a universe promise (override_table_universe)."""

    def __init__(self, scope: "Scope", source: Node) -> None:
        super().__init__(scope, [source], source.arity)

    def process(self, time: int) -> DeltaBatch:
        return self.take(0)


class InputMirrors:
    """Own per-port input-state mirrors for state-peeking operators.

    Under sharded execution a local input REPLICA's ``current`` holds the
    shard of the keys IT processed, which diverges from the consumer's
    shard whenever an upstream reindex changed keys — so sharded scopes
    read OWN mirrors built from the batches routed here by row key.
    Single-worker scopes read the input's complete ``current`` directly
    (no memory duplication)."""

    def _init_mirrors(self) -> None:
        self._mirrors: list[dict] = [{} for _ in self.inputs]

    def _input_state(self, port: int) -> dict:
        if self.scope.sharded:
            return self._mirrors[port]
        return self.inputs[port].current

    def _absorb(self, port: int, batch: DeltaBatch) -> None:
        if self.scope.sharded:
            apply_batch_to_state(self._mirrors[port], batch)


class ZipNode(InputMirrors, Node):
    """Zip same-universe tables into one storage (column concatenation).

    The reference reaches the same goal by flattening same-universe columns
    into shared tuple storage (graph_runner/path_evaluator.py); here it is an
    explicit operator: a row is emitted once every input holds the key, so a
    base table zipped with tables over a superset universe restricts
    naturally.
    """

    STATE_ATTRS = ("_mirrors",)

    def __init__(self, scope: "Scope", sources: Sequence[Node]) -> None:
        super().__init__(scope, list(sources), sum(s.arity for s in sources))
        self._init_mirrors()

    def _combined(self, key: Pointer) -> tuple | None:
        parts = []
        for port in range(len(self.inputs)):
            row = self._input_state(port).get(key)
            if row is None:
                return None
            parts.append(row)
        return tuple(v for part in parts for v in part)

    def process(self, time: int) -> DeltaBatch:
        affected: set[Pointer] = set()
        for port in range(len(self.inputs)):
            batch = self.take(port)
            self._absorb(port, batch)
            for key, _row, _diff in batch:
                affected.add(key)
        out = DeltaBatch()
        state = self.current  # hoisted: drains lazy state once
        for key in affected:
            old = state.get(key)
            new = self._combined(key)
            if old is not None and rows_differ(old, new):
                out.append(key, old, -1)
            if new is not None and rows_differ(old, new):
                out.append(key, new, 1)
        return out


class JoinKind:
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    OUTER = "outer"


_JOIN_SALT = b"join"
_JOIN_LEFT_SALT = b"join-left"
_JOIN_RIGHT_SALT = b"join-right"


def join_result_key(lkey: Pointer | None, rkey: Pointer | None) -> Pointer:
    if lkey is not None and rkey is not None:
        return hash_values((lkey, rkey), salt=_JOIN_SALT)
    if lkey is not None:
        return hash_values((lkey,), salt=_JOIN_LEFT_SALT)
    assert rkey is not None
    return hash_values((rkey,), salt=_JOIN_RIGHT_SALT)


def _keys_unique(kb: np.ndarray, n: int) -> bool:
    """Vectorized uniqueness screen over (n,16) key bytes. Keys are
    uniform 128-bit content hashes, so low-64-bit uniqueness implies full
    uniqueness; only the ~n²/2⁶⁵ collision case pays the full check."""
    if n < 2:
        return True
    lo = np.sort(np.ascontiguousarray(kb[:, :8]).view(np.uint64).ravel())
    if not (lo[1:] == lo[:-1]).any():
        return True
    v = np.ascontiguousarray(kb).view(np.dtype((np.void, 16))).ravel()
    return len(np.unique(v)) == n


class _JoinSide:
    """One side's rows in columnar form: join-key arrays (one per key
    column), key bytes, and the full column set (object arrays where a
    column isn't clean). Unified-dtype key casts and the NaN screen are
    cached per side AND per key column, so probing a long-lived block
    costs the cast/scan once, not once per commit."""

    __slots__ = (
        "n", "jks", "kb", "cols", "dev_jks", "_jk_int", "_jk_f64", "_nan"
    )

    def __init__(self, n, jks, kb, cols, dev_jks=None) -> None:
        self.n = n
        self.jks = jks
        self.kb = kb
        self.cols = cols
        #: device twins of the join-key arrays (one per key column, or
        #: None) — set only when the batch arrived device-resident with
        #: int64 keys, so the device matcher can skip the H2D re-upload
        self.dev_jks = dev_jks
        self._jk_int: dict[int, np.ndarray] = {}
        self._jk_f64: dict[int, Any] = {}  # False = not representable
        self._nan: dict[int, bool] = {}

    def jk_has_nan(self, i: int = 0) -> bool:
        got = self._nan.get(i)
        if got is None:
            jk = self.jks[i]
            got = self._nan[i] = (
                jk.dtype.kind == "f" and bool(np.isnan(jk).any())
            )
        return got

    def jk_int(self, i: int = 0) -> np.ndarray:
        got = self._jk_int.get(i)
        if got is None:
            jk = self.jks[i]
            got = self._jk_int[i] = (
                jk if jk.dtype == np.int64 else jk.astype(np.int64)
            )
        return got

    def jk_f64(self, i: int = 0) -> np.ndarray | None:
        got = self._jk_f64.get(i)
        if got is None:
            jk = self.jks[i]
            if jk.dtype.kind == "i" and jk.size:
                amax = int(np.abs(jk).max())
                if amax < 0 or amax > _JOIN_FLOAT_EXACT:
                    self._jk_f64[i] = False  # would round in float64
                    return None
            cast = jk if jk.dtype == np.float64 else jk.astype(np.float64)
            got = self._jk_f64[i] = (
                False if bool(np.isnan(cast).any()) else cast
            )
        return None if got is False else got


_JOIN_FLOAT_EXACT = 1 << 53


def _device_ops_active():
    """The device_ops module when the JAX operator kernels may engage,
    else None.  The disabled case is one cached env check — the PR-2
    zero-overhead discipline for escape-hatched machinery."""
    from pathway_tpu.engine import device_ops as _dops

    return _dops if _dops.enabled() else None


def _unify_join_col(a: "_JoinSide", b: "_JoinSide", i: int):
    """Key column ``i`` of two sides cast to one comparison dtype matching
    Python dict-key equality (True == 1 == 1.0), or None when vectorized
    equality would diverge (NaN identity, huge ints in float64, or
    cross-kind pairs like str vs int — route those to the dict path)."""
    ajk, bjk = a.jks[i], b.jks[i]
    ka, kb_ = ajk.dtype.kind, bjk.dtype.kind
    if ka == kb_:
        if ka == "f" and (a.jk_has_nan(i) or b.jk_has_nan(i)):
            return None
        return ajk, bjk
    kinds = {ka, kb_}
    if kinds <= {"b", "i"}:
        return a.jk_int(i), b.jk_int(i)
    if kinds <= {"b", "i", "f"}:
        a2, b2 = a.jk_f64(i), b.jk_f64(i)
        if a2 is None or b2 is None:
            return None
        return a2, b2
    return None


def _unify_join_keys(a: "_JoinSide", b: "_JoinSide"):
    """Per-key-column unification: (left arrays, right arrays) or None."""
    left: list[np.ndarray] = []
    right: list[np.ndarray] = []
    for i in range(len(a.jks)):
        uni = _unify_join_col(a, b, i)
        if uni is None:
            return None
        left.append(uni[0])
        right.append(uni[1])
    return left, right


def _match_join_pairs(la: np.ndarray, ra: np.ndarray):
    """Index pairs (l_idx, r_idx) of all equal-key matches — a sort-based
    hash-join core; the smaller side becomes the sorted haystack."""
    empty = np.empty(0, np.int64)
    if len(la) == 0 or len(ra) == 0:
        return empty, empty
    if len(ra) > len(la):
        r_idx, l_idx = _match_join_pairs(ra, la)
        return l_idx, r_idx
    order = np.argsort(ra, kind="stable")
    rs = ra[order]
    lo = np.searchsorted(rs, la, "left")
    hi = np.searchsorted(rs, la, "right")
    counts = hi - lo
    total = int(counts.sum())
    if total == 0:
        return empty, empty
    l_idx = np.repeat(np.arange(len(la)), counts)
    starts = np.repeat(lo, counts)
    csum = np.cumsum(counts) - counts
    offs = np.arange(total) - np.repeat(csum, counts)
    return l_idx, order[starts + offs]


def _as_match_codes(arr: np.ndarray) -> np.ndarray | None:
    """Reinterpret a join-key column as int64 codes whose equality is
    exactly the column's value equality, or ``None`` when no such view
    exists. Integers widen losslessly; uint64 reinterprets bitwise (a
    bijection, so equality is preserved); floats widen to float64 (exact
    for every narrower float), normalise -0.0 to +0.0 via ``+ 0.0``, and
    reinterpret bits — sound only when NaN-free, since bit equality would
    call equal-bit NaNs a match."""
    k = arr.dtype.kind
    if k in "bi":
        return np.ascontiguousarray(arr, np.int64)
    if k == "u":
        if arr.dtype.itemsize == 8:
            return np.ascontiguousarray(arr).view(np.int64)
        return np.ascontiguousarray(arr, np.int64)
    if k == "f":
        f = np.ascontiguousarray(arr, np.float64) + 0.0
        if np.isnan(f).any():
            return None
        return f.view(np.int64)
    return None


def _match_join_pairs_multi(
    l_arrays: "list[np.ndarray]", r_arrays: "list[np.ndarray]"
):
    """Multi-column join matching: reduce key TUPLES to joint integer
    codes (factorized over the concatenation of both sides, so equal
    tuples get equal codes across sides), then run the single-array
    sort-based matcher. Columns arrive already dtype-unified.

    With the native kernels loaded and every key column int64-codeable,
    one hash-table kernel replaces the factorize + argsort + searchsorted
    pipeline; its output ordering (probe index ascending, build index
    ascending within a probe row) is the sort-based matcher's ordering,
    so the paths are interchangeable pair for pair."""
    from pathway_tpu.native import kernels as _native

    if _native is not None and hasattr(_native, "match_pairs_i64"):
        lc = [_as_match_codes(a) for a in l_arrays]
        if all(c is not None for c in lc):
            rc = [_as_match_codes(a) for a in r_arrays]
            if all(c is not None for c in rc):
                return _native.match_pairs_i64(lc, rc)
    from pathway_tpu.engine.device import factorize_multi

    if len(l_arrays) == 1:
        return _match_join_pairs(l_arrays[0], r_arrays[0])
    nl = len(l_arrays[0])
    both = [
        np.concatenate([la, ra]) for la, ra in zip(l_arrays, r_arrays)
    ]
    _first, inverse = factorize_multi(both)
    return _match_join_pairs(inverse[:nl], inverse[nl:])


def _hash_join_pairs_py(lkb: np.ndarray, rkb: np.ndarray) -> np.ndarray:
    """Python fallback for the vectorized join_result_key derivation."""
    import hashlib

    n = len(lkb)
    out = np.empty((n, 16), np.uint8)
    lmem, rmem = lkb.tobytes(), rkb.tobytes()
    for i in range(n):
        h = hashlib.blake2b(digest_size=16, person=b"pw-tpu-key")
        h.update(
            b"join\x04"
            + lmem[i * 16 : i * 16 + 16]
            + b"\x04"
            + rmem[i * 16 : i * 16 + 16]
        )
        out[i] = np.frombuffer(h.digest(), np.uint8)
    return out


def _entry_keys_bytes_py(entries: list) -> np.ndarray | None:
    if any(type(e[0]) is not Pointer for e in entries):
        return None
    buf = b"".join(int(e[0]).to_bytes(16, "little") for e in entries)
    return np.frombuffer(buf, np.uint8).reshape(len(entries), 16)


class JoinNode(Node):
    """Equality join with incremental per-group recomputation.

    Output rows are ``left_row + right_row`` with ``None`` padding on the
    unmatched side for outer kinds; result ids derive from the source ids
    (reference: join_tables python_api.rs:2986, dataflow join at
    dataflow.rs:2320+). ``id_from_left`` keeps the left row id (used by
    id-preserving joins such as ``ix``-style lookups and asof_now joins).

    Single-key inner joins run fully columnar while their input stays
    insert-only: arrangements are kept as columnar blocks, each commit is
    one sort-based NumPy hash join plus a vectorized BLAKE2b pass for the
    result keys, and the output is a columnar batch (no per-row Python
    objects). The first batch that needs exact row semantics (retraction,
    outer kind, exotic key) materialises the blocks into the dict
    arrangements once and the incremental row path takes over.
    """

    STATE_ATTRS = ("left_arr", "right_arr")

    def __init__(
        self,
        scope: "Scope",
        left: Node,
        right: Node,
        left_on: Sequence[int],
        right_on: Sequence[int],
        kind: str = JoinKind.INNER,
        id_from_left: bool = False,
        left_keys_repeat: bool = True,
        id_spec: tuple | None = None,
    ) -> None:
        super().__init__(scope, [left, right], left.arity + right.arity)
        self.left_on = list(left_on)
        self.right_on = list(right_on)
        self.kind = kind
        #: result-id source: None -> pair hash; ("left"/"right", None) ->
        #: that side's row key; ("left"/"right", col) -> that side's
        #: pointer column (reference join id= assignment)
        if id_spec is None and id_from_left:
            id_spec = ("left", None)
        self.id_spec = id_spec
        self.id_from_left = id_spec == ("left", None)
        # join-key → {row_key: row}
        self.left_arr: dict[Any, dict[Pointer, tuple]] = {}
        self.right_arr: dict[Any, dict[Pointer, tuple]] = {}
        # columnar arrangements (lists of _JoinSide blocks), active until
        # a batch forces the dict path
        self._blocks_left: list[_JoinSide] = []
        self._blocks_right: list[_JoinSide] = []
        #: custom-id joins: result id -> owning join-key group, so
        #: duplicate ids are caught ACROSS groups, not only within one;
        #: suppressed contenders wait in _id_waiters and are re-examined
        #: when the owner releases the id
        self._id_owners: dict[Pointer, Any] = {}
        self._id_waiters: dict[Pointer, set] = {}
        self._columnar_ok = (
            kind == JoinKind.INNER
            and id_spec is None
            and len(self.left_on) >= 1
            and len(self.left_on) == len(self.right_on)
        )

    def _okey(
        self,
        lk: Pointer | None,
        rk: Pointer | None,
        lrow: tuple | None,
        rrow: tuple | None,
        report: bool = True,
    ) -> Pointer:
        """Result row id per id_spec; an id_spec pointing at a side that
        is absent (outer padding) falls back to the pair hash.
        ``report=False`` on snapshot passes (old-state recomputation) so
        one bad row is reported once per batch, not once per pass."""
        spec = self.id_spec
        if spec is not None:
            side, col = spec
            v: Any = None
            if side == "left" and lk is not None:
                v = lk if col is None else lrow[col]
            elif side == "right" and rk is not None:
                v = rk if col is None else rrow[col]
            if isinstance(v, Pointer):
                return v
            if v is not None or (
                side == "left" and lk is not None
            ) or (side == "right" and rk is not None):
                # None / non-pointer id value: poison, don't emit a
                # non-Pointer row key into the dataflow
                if report:
                    self.report(
                        lk if lk is not None else rk,
                        f"join id= value is not a pointer: {v!r}",
                    )
                return None  # caller drops the row
        return join_result_key(lk, rk)

    # -- columnar fast path -------------------------------------------------

    def _side_from_batch(
        self, batch: DeltaBatch, on_cols: Sequence[int], arity: int
    ) -> _JoinSide | None:
        from pathway_tpu.engine import device
        from pathway_tpu.native import kernels as _native

        n = len(batch)
        if n == 0:
            return _JoinSide(0, None, None, [])
        payload = batch.columns
        if payload is not None:
            if payload.diffs is not None and not (payload.diffs == 1).all():
                return None
            jks = [payload.cols[c] for c in on_cols]
            if any(jk.dtype.kind not in "bifU" for jk in jks):
                return None
            try:
                kb = payload.kbytes()
            except (OverflowError, TypeError):
                return None
            if kb is None:
                return None
            if not batch._insert_only and not _keys_unique(kb, n):
                return None
            # a device-resident delivery with a single int64 key column
            # carries a device twin of the join keys: the matcher can
            # consume it in place of re-uploading (int64 only — float
            # code derivation normalises bits, so twins there are unsafe
            # and match_pairs re-validates by object identity anyway)
            dev_jks = None
            if (
                len(on_cols) == 1
                and jks[0].dtype == np.int64
                and getattr(payload, "resident", None) is not None
                and payload.resident()
            ):
                try:
                    twin = payload.device_column(on_cols[0])
                except Exception:
                    twin = None
                if twin is not None:
                    dev_jks = [twin]
            return _JoinSide(
                n, jks, kb, list(payload.cols), dev_jks=dev_jks
            )
        entries = batch.entries
        if _native is not None and hasattr(_native, "entries_to_side"):
            # one pass over the rows screens diffs/keys and fills every
            # column typed (int64/float64/bool) or exact-object — no
            # ColumnarView scan, no per-column list comprehension
            got = _native.entries_to_side(
                entries, list(on_cols), arity, Pointer
            )
            if got is not None:
                kb, cols = got
                if not batch._insert_only and not _keys_unique(kb, n):
                    return None
                return _JoinSide(n, [cols[c] for c in on_cols], kb, cols)
        view = device.ColumnarView(entries, from_entries=True)
        jks = []
        for c in on_cols:
            jk = view.column(c)
            if jk is None or jk.dtype.kind not in "bifU":
                return None
            jks.append(jk)
        if _native is not None:
            diffs = _native.entry_diffs(entries)
            if not (diffs == 1).all():
                return None
            kb = _native.entry_keys_bytes(entries, Pointer)
        else:
            if any(e[2] != 1 for e in entries):
                return None
            kb = _entry_keys_bytes_py(entries)
        if kb is None:
            return None
        if not batch._insert_only and not _keys_unique(kb, n):
            # _raw_insert_only skipped the consolidate uniqueness scan;
            # duplicate (key,row) pairs would collapse lossily at the
            # dict-arrangement handover, so screen keys here
            return None
        cols = []
        for c in range(arity):
            col = view.column(c)
            if col is None:
                arr = np.empty(n, object)
                arr[:] = [e[1][c] for e in entries]
                col = arr
            cols.append(col)
        return _JoinSide(n, jks, kb, cols)

    def _emit_part(
        self,
        lside: _JoinSide,
        rside: _JoinSide,
        l_idx: np.ndarray,
        r_idx: np.ndarray,
    ):
        from pathway_tpu.engine.batch import Columns
        from pathway_tpu.native import kernels as _native

        lkb = np.ascontiguousarray(lside.kb[l_idx])
        rkb = np.ascontiguousarray(rside.kb[r_idx])

        def pair_keys() -> np.ndarray:
            # the vectorized BLAKE2b pass over the pair keys is the join's
            # single biggest fixed cost — run it only when the output keys
            # are actually observed (sink, state read, downstream keying)
            if _native is not None:
                return _native.hash_join_pairs(lkb, rkb)
            return _hash_join_pairs_py(lkb, rkb)

        cols = [c[l_idx] for c in lside.cols] + [
            c[r_idx] for c in rside.cols
        ]
        return Columns(len(l_idx), cols, kb_thunk=pair_keys)

    def _process_columnar_inner(
        self, left_batch: DeltaBatch, right_batch: DeltaBatch
    ) -> DeltaBatch | None:
        """Bilinear delta join over columnar blocks:
        ``ΔL⋈ΔR + ΔL⋈R + L⋈ΔR``. None → caller falls back to the dict
        path (state untouched: all screens run before any block append)."""
        from pathway_tpu.engine.batch import Columns

        ls = self._side_from_batch(
            left_batch, self.left_on, self.inputs[0].arity
        )
        rs = self._side_from_batch(
            right_batch, self.right_on, self.inputs[1].arity
        )
        if ls is None or rs is None:
            return None
        plan: list[tuple[_JoinSide, _JoinSide]] = []
        if rs.n:
            plan.extend((blk, rs) for blk in self._blocks_left)
        if ls.n:
            plan.extend((ls, blk) for blk in self._blocks_right)
        if ls.n and rs.n:
            plan.append((ls, rs))
        matches = []
        # measurement-driven placement of the pair matcher: the device
        # matcher is pair-for-pair identical to the host one, so the
        # choice is pure economics (observed ns/row each side)
        _dops = _device_ops_active() if plan else None
        use_device = False
        t0_ns = 0
        if _dops is not None:
            from pathway_tpu.optimize.placement import POLICY

            match_rows = sum(l.n + r.n for l, r in plan)
            t0_ns = _time.perf_counter_ns()
            use_device = POLICY.choose("join", self.index, match_rows)
        for l, r in plan:
            uni = _unify_join_keys(l, r)
            if uni is None:
                return None
            got = None
            if use_device:
                # hand the matcher any device key twins whose host array
                # IS the unified array (identity — unification that cast
                # or copied invalidates the twin)
                l_dev = r_dev = None
                if (
                    l.dev_jks is not None
                    and len(l.jks) == 1
                    and uni[0][0] is l.jks[0]
                ):
                    l_dev = l.dev_jks[0]
                if (
                    r.dev_jks is not None
                    and len(r.jks) == 1
                    and uni[1][0] is r.jks[0]
                ):
                    r_dev = r.dev_jks[0]
                try:
                    got = _dops.match_pairs(
                        uni[0], uni[1], l_dev=l_dev, r_dev=r_dev
                    )
                except Exception:
                    got = None  # device trouble: host matcher is the spec
            if got is None:
                l_idx, r_idx = _match_join_pairs_multi(*uni)
            else:
                l_idx, r_idx = got
            if len(l_idx):
                matches.append((l, r, l_idx, r_idx))
        if _dops is not None:
            POLICY.record(
                "join",
                self.index,
                use_device,
                match_rows,
                _time.perf_counter_ns() - t0_ns,
            )
        # all screens passed: commit the block appends, then emit
        if ls.n:
            self._blocks_left.append(ls)
        if rs.n:
            self._blocks_right.append(rs)
        parts = [
            self._emit_part(l, r, l_idx, r_idx)
            for l, r, l_idx, r_idx in matches
        ]
        if not parts:
            return DeltaBatch()
        payload = parts[0] if len(parts) == 1 else Columns.concat(parts)
        if payload is not None:
            return DeltaBatch.from_columns(
                payload, consolidated=True, insert_only=True
            )
        # cross-part dtype drift: materialise rows (correct, slower)
        out = DeltaBatch()
        for p in parts:
            out.entries.extend(
                DeltaBatch.from_columns(p, consolidated=True).entries
            )
        out._consolidated = True
        out._insert_only = True
        return out

    def _ensure_dict_arrangements(self) -> None:
        """Materialise columnar blocks into the dict arrangements (once),
        handing over to the incremental row path."""
        if not self._columnar_ok:
            return
        self._columnar_ok = False
        self._materialize_blocks_into(self.left_arr, self.right_arr)
        self._blocks_left.clear()
        self._blocks_right.clear()

    def _materialize_blocks_into(self, left_arr: dict, right_arr: dict) -> None:
        from pathway_tpu.engine.batch import Columns

        for blocks, arr in (
            (self._blocks_left, left_arr),
            (self._blocks_right, right_arr),
        ):
            for side in blocks:
                entries = Columns(
                    side.n, side.cols, kbytes=side.kb
                ).to_entries()
                jk_lists = zip(*(a.tolist() for a in side.jks))
                for (key, row, _d), jkv in zip(entries, jk_lists):
                    arr.setdefault(jkv, {})[key] = row

    def op_state(self) -> dict:
        # snapshot a dict VIEW of the arrangements without degrading the
        # live columnar blocks (mirrors GroupbyNode.op_state)
        state = {"current": dict(self.current)}
        if self._columnar_ok and (self._blocks_left or self._blocks_right):
            left: dict = {k: dict(v) for k, v in self.left_arr.items()}
            right: dict = {k: dict(v) for k, v in self.right_arr.items()}
            self._materialize_blocks_into(left, right)
            state["left_arr"] = left
            state["right_arr"] = right
        else:
            state["left_arr"] = self.left_arr
            state["right_arr"] = self.right_arr
        return state

    def restore_op_state(self, state: dict) -> None:
        super().restore_op_state(state)
        self._blocks_left.clear()
        self._blocks_right.clear()
        if self.left_arr or self.right_arr:
            self._columnar_ok = False

    def _jk(self, row: tuple, cols: Sequence[int], key: Pointer) -> Any:
        vals = tuple(row[c] for c in cols)
        if any(is_error(v) for v in vals):
            self.report(key, "error value in join key")
            return ERROR
        try:
            hash(vals)
        except TypeError:
            vals = tuple(repr(v) for v in vals)
        return vals

    def _local_output(
        self, jk: Any, report: bool = True
    ) -> dict[Pointer, tuple]:
        lrows = self.left_arr.get(jk, {})
        rrows = self.right_arr.get(jk, {})
        out: dict[Pointer, tuple] = {}
        l_pad = (None,) * self.inputs[0].arity
        r_pad = (None,) * self.inputs[1].arity
        custom = self.id_spec is not None

        def put(okey: Pointer | None, row: tuple) -> None:
            if okey is None:
                return  # poisoned id value, reported in _okey
            if custom:
                owner = self._id_owners.get(okey, jk)
                if okey in out or owner != jk:
                    # the reference errors on duplicate result ids; here
                    # the row poisons via the error log (within AND
                    # across join-key groups) and the first row wins
                    if report:
                        self.report(okey, "duplicate join result id")
                        if owner != jk:
                            # remember the contender: if the owner ever
                            # releases the id, this group re-emits
                            self._id_waiters.setdefault(
                                okey, set()
                            ).add(jk)
                    return
            out[okey] = row

        if lrows and rrows:
            for lk, lrow in lrows.items():
                for rk, rrow in rrows.items():
                    put(
                        self._okey(lk, rk, lrow, rrow, report),
                        lrow + rrow,
                    )
        if self.kind in (JoinKind.LEFT, JoinKind.OUTER) or (
            self.id_from_left and self.kind != JoinKind.INNER
        ):
            if not rrows:
                for lk, lrow in lrows.items():
                    put(
                        self._okey(lk, None, lrow, None, report),
                        lrow + r_pad,
                    )
        if self.kind in (JoinKind.RIGHT, JoinKind.OUTER) and not self.id_from_left:
            if not lrows:
                for rk, rrow in rrows.items():
                    put(
                        self._okey(None, rk, None, rrow, report),
                        l_pad + rrow,
                    )
        return out

    def _process_insert_only_inner(
        self, left_batch: DeltaBatch, right_batch: DeltaBatch
    ) -> DeltaBatch | None:
        """Incremental inner-join fast path for insert-only deltas:
        ``ΔL⋈R + L⋈(R+ΔR)`` — no per-group recompute, no old/new diffing,
        no consolidation pass (result keys are unique pair hashes). This
        is the bulk-load hot path; the general path below handles
        retractions and outer kinds. Returns None (state untouched) for
        multiplicities > 1, which the pair-emitting loops and the dict
        arrangements cannot represent."""
        from pathway_tpu.native import kernels as _native

        if _native is not None:
            entries = _native.join_insert_inner(
                left_batch.entries,
                right_batch.entries,
                self.left_on,
                self.right_on,
                self.left_arr,
                self.right_arr,
                ERROR,
                Pointer,
                None,  # lazy node state: scheduler defers the application
                join_result_key,
            )
            if entries is not None:
                out = DeltaBatch()
                out.entries = entries
                out._consolidated = True
                out._insert_only = True
                return out
            # non-scalar / ERROR join keys: Python keeps exact semantics
        if any(e[2] != 1 for e in left_batch.entries) or any(
            e[2] != 1 for e in right_batch.entries
        ):
            return None
        out = DeltaBatch()
        append = out.entries.append
        # ΔR pairs with the PRE-delta left arrangement...
        for rkey, rrow, _diff in right_batch:
            jk = self._jk(rrow, self.right_on, rkey)
            if jk is ERROR:
                continue
            lrows = self.left_arr.get(jk)
            if lrows:
                for lk, lrow in lrows.items():
                    append((join_result_key(lk, rkey), lrow + rrow, 1))
            self.right_arr.setdefault(jk, {})[rkey] = rrow
        # ...then ΔL pairs with the post-delta right arrangement, so
        # ΔL×ΔR pairs appear exactly once
        for lkey, lrow, _diff in left_batch:
            jk = self._jk(lrow, self.left_on, lkey)
            if jk is ERROR:
                continue
            rrows = self.right_arr.get(jk)
            if rrows:
                for rk, rrow in rrows.items():
                    append((join_result_key(lkey, rk), lrow + rrow, 1))
            self.left_arr.setdefault(jk, {})[lkey] = lrow
        out._consolidated = True
        out._insert_only = True
        return out

    def process(self, time: int) -> DeltaBatch:
        # raw takes: the columnar path is multiset-correct, so the
        # consolidation scan is skipped entirely while it holds
        left_batch = self.take_raw(0)
        right_batch = self.take_raw(1)
        if self._columnar_ok:

            def insertish(b: DeltaBatch) -> bool:
                return b._raw_insert_only or b._insert_only or not b

            if not (insertish(left_batch) and insertish(right_batch)):
                # hint absent ≠ retractions present (e.g. a row-path
                # expression output): consolidation may prove the batch
                # insert-only and keep the columnar join alive
                left_batch = left_batch.consolidate()
                right_batch = right_batch.consolidate()
            if insertish(left_batch) and insertish(right_batch):
                out = self._process_columnar_inner(left_batch, right_batch)
                if out is not None:
                    return out
            # this batch needs exact row semantics: hand the columnar
            # blocks to the dict arrangements (once) and fall through
            self._ensure_dict_arrangements()
        left_batch = left_batch.consolidate()
        right_batch = right_batch.consolidate()
        fast = (
            self.kind == JoinKind.INNER
            and self.id_spec is None
            and (left_batch._insert_only or not left_batch)
            and (right_batch._insert_only or not right_batch)
        )
        if fast:
            out = self._process_insert_only_inner(left_batch, right_batch)
            if out is not None:
                return out
        affected: set[Any] = set()
        old_local: dict[Any, dict[Pointer, tuple]] = {}

        def note(jk: Any) -> None:
            if jk is not ERROR and jk not in old_local:
                # snapshot pass: suppress reports (the new-state pass
                # reports each problem exactly once per batch)
                old_local[jk] = self._local_output(jk, report=False)
                affected.add(jk)

        staged: list[tuple[int, Any, Pointer, tuple, int]] = []
        for key, row, diff in left_batch:
            jk = self._jk(row, self.left_on, key)
            note(jk)
            staged.append((0, jk, key, row, diff))
        for key, row, diff in right_batch:
            jk = self._jk(row, self.right_on, key)
            note(jk)
            staged.append((1, jk, key, row, diff))

        for side, jk, key, row, diff in staged:
            if jk is ERROR:
                continue
            arr = self.left_arr if side == 0 else self.right_arr
            group = arr.setdefault(jk, {})
            if diff > 0:
                group[key] = row
            else:
                group.pop(key, None)
                if not group:
                    arr.pop(jk, None)

        out = DeltaBatch()
        freed: list[Pointer] = []
        # custom-id joins must visit groups deterministically: with
        # duplicate result ids the winner is the first group PROCESSED,
        # and set order is per-process hash order (str hashes are salted)
        # — sorting pins the winner across runs, processes and insertion
        # orders
        if self.id_spec is not None:
            affected = sorted(affected, key=repr)
        for jk in affected:
            old = old_local[jk]
            new = self._local_output(jk)
            if self.id_spec is not None:
                for okey in old:
                    if okey not in new and self._id_owners.get(okey) == jk:
                        del self._id_owners[okey]
                        if okey in self._id_waiters:
                            freed.append(okey)
                for okey in new:
                    self._id_owners[okey] = jk
            for okey, orow in old.items():
                if okey not in new or rows_differ(new[okey], orow):
                    out.append(okey, orow, -1)
            for okey, orow in new.items():
                if okey not in old or rows_differ(old[okey], orow):
                    out.append(okey, orow, 1)
        # a released custom id hands over to a suppressed contender:
        # without this, the contender's row would stay missing until an
        # unrelated update happened to touch its join-key group
        for okey in freed:
            if self._id_owners.get(okey) is not None:
                continue  # re-claimed within this batch
            for jk in sorted(
                self._id_waiters.pop(okey, ()), key=repr
            ):
                if jk in affected:
                    continue  # its recompute already saw the free id
                candidate = self._local_output(jk, report=False)
                row = candidate.get(okey)
                if row is not None:
                    self._id_owners[okey] = jk
                    out.append(okey, row, 1)
                    break
        return out.consolidate()


def _groupby_batch_arrays(
    batch: DeltaBatch, by_cols: Sequence[int], sum_cols: Sequence[int]
):
    """Extract ``(by arrays, diffs, sum value arrays)`` for a vectorized
    groupby pass — shared by the columnar state machine and the
    degraded-mode vectorized path so their cleanliness screens can never
    diverge. Returns None whenever the batch is not cleanly columnar:
    mixed/object dtypes, NaN group values (np.unique collapses NaNs while
    the row path groups them by bit pattern), non-numeric sum columns."""
    from pathway_tpu.engine import device
    from pathway_tpu.native import kernels as _native

    cols = batch.columns
    if cols is not None:
        bys = [cols.cols[c] for c in by_cols]
        if any(by.dtype.kind not in "bifU" for by in bys):
            return None
        diffs = cols.diffs
        getcol = lambda c: cols.cols[c]  # noqa: E731
    else:
        entries = batch.entries
        view = device.ColumnarView(entries, from_entries=True)
        bys = []
        for c in by_cols:
            by = view.column(c)
            if by is None or by.dtype.kind not in "bifU":
                return None
            bys.append(by)
        if _native is not None:
            diffs = _native.entry_diffs(entries)
        else:
            diffs = np.fromiter(
                (d for _k, _r, d in entries), np.int64, len(entries)
            )
        getcol = view.column
    if any(
        by.dtype.kind == "f" and np.isnan(by).any() for by in bys
    ):
        return None
    vals = []
    for c in sum_cols:
        if c < 0:
            vals.append(None)
            continue
        col = getcol(c)
        if col is None or col.dtype.kind not in "bif":
            return None
        vals.append(col)
    if diffs is None:
        diffs = np.ones(len(bys[0]), np.int64)
    return bys, diffs, vals


def _factorize_bys(bys: "list[np.ndarray]"):
    """``(raw tuples, inverse)`` of the distinct by-value tuples in a
    batch — single-column keeps the cheap ``np.unique`` path."""
    from pathway_tpu.engine.device import factorize, factorize_multi

    if len(bys) == 1:
        uniq, inverse = factorize(bys[0])
        return [(v,) for v in uniq], inverse.reshape(-1)
    first, inverse = factorize_multi(bys)
    return list(zip(*(by[first].tolist() for by in bys))), inverse


class _ColumnarGroups:
    """Fully columnar group state for count/sum groupbys over clean by
    columns (one or several).

    Replaces the per-group Python objects (dict entry + reducer states +
    tuple rebuilds) with flat arrays: ``member`` (signed multiplicity) and
    one accumulator array per sum reducer, indexed by a dense group id.
    A streaming delta commit then costs one factorization (``np.unique``,
    composite codes for multi-by) + segment reductions + O(touched
    groups) array math — the reference's semigroup reducer update
    (src/engine/reduce.rs:78) at NumPy speed.

    Any batch the arrays cannot represent exactly (mixed/object dtypes,
    NaN group values, ERROR cells, int64 overflow risk) makes the owner
    degrade to the dict-of-states row path BEFORE any mutation, via
    :meth:`materialize`.
    """

    __slots__ = (
        "by_cols",
        "_single",
        "gkey_salt",
        "kinds",
        "sum_cols",
        "index",
        "by_raw",
        "gkeys",
        "member",
        "accs",
        "size",
    )

    _CAP0 = 1024

    def __init__(
        self,
        by_cols: Sequence[int],
        reducers: Sequence[tuple[Reducer, Sequence[int]]],
        gkey_salt: bytes = b"",
    ) -> None:
        from pathway_tpu.engine.reducers import ReducerKind

        self.by_cols = list(by_cols)
        self.gkey_salt = gkey_salt
        # single-by state stores bare scalars in index/by_raw (tuple
        # wrapping + tuple hashing per touched group measurably drags
        # the incremental hot path); multi-by stores value tuples
        self._single = len(self.by_cols) == 1
        self.kinds = [r.kind for r, _c in reducers]
        self.sum_cols = [
            cols[0] if r.kind == ReducerKind.SUM else -1
            for r, cols in reducers
        ]
        self.index: dict[Any, int] = {}  # normalised by-value(s) -> group id
        self.by_raw: list[Any] = []  # first-seen raw by-value(s) per group
        self.gkeys: list[Pointer] = []
        self.member = np.zeros(self._CAP0, np.int64)
        self.accs: list[np.ndarray | None] = [
            np.zeros(self._CAP0, np.int64) if c >= 0 else None
            for c in self.sum_cols
        ]
        self.size = 0

    @staticmethod
    def _norm_one(v: Any) -> Any:
        """Group-identity key matching hash_values equivalence: bools are
        tagged apart from ints, int-valued floats collapse onto ints."""
        if isinstance(v, bool):
            return ("\x01b", v)
        if isinstance(v, float) and -(2**63) < v < 2**63 and v == int(v):
            return int(v)
        return v

    def _norm(self, raw: Any) -> Any:
        """Raw by-value (scalar for single-by, tuple for multi-by) -> the
        index key under hash_values-equivalent identity."""
        if self._single:
            return self._norm_one(raw)
        return tuple(map(self._norm_one, raw))

    def _grow(self, need: int) -> None:
        cap = len(self.member)
        if need <= cap:
            return
        while cap < need:
            cap *= 2
        member = np.zeros(cap, np.int64)
        member[: self.size] = self.member[: self.size]
        self.member = member
        for i, acc in enumerate(self.accs):
            if acc is not None:
                grown = np.zeros(cap, acc.dtype)
                grown[: self.size] = acc[: self.size]
                self.accs[i] = grown

    def _batch_arrays(self, batch: DeltaBatch):
        """(by arrays, diffs, sum value arrays) or None when not cleanly
        columnar."""
        return _groupby_batch_arrays(batch, self.by_cols, self.sum_cols)

    def process_batch(self, batch: DeltaBatch, node: "GroupbyNode"):
        """Apply one delta batch; returns the output DeltaBatch, or None to
        signal degradation (state untouched)."""
        from pathway_tpu.engine import device
        from pathway_tpu.engine.batch import Columns
        from pathway_tpu.engine.reducers import ReducerKind

        got = self._batch_arrays(batch)
        if got is None:
            return None
        bys, diffs, vals = got
        n = len(bys[0])
        if n == 0:
            return DeltaBatch()
        dmax = int(np.abs(diffs).max()) if n else 0
        if dmax < 0:  # abs(INT64_MIN) wraps
            return None
        for col in vals:
            if col is not None and device.int_sum_overflow_risk(col, n, dmax):
                return None
        if self._single:
            raws, inverse = device.factorize(bys[0])
            inverse = inverse.reshape(-1)
        else:
            raws, inverse = _factorize_bys(bys)
        nu = len(raws)
        # device placement: launch the segment reductions as one batch of
        # device scatter-adds and fetch AFTER the group-id resolution loop
        # below, so the kernels overlap the host dict walk; any device
        # trouble falls back to the host kernels (the bit-exact spec)
        job = None
        gdiffs = None
        deltas: list[np.ndarray | None] = []
        gb_idx = node.index if isinstance(node.index, int) else -1
        t0_ns = 0
        _dops = _device_ops_active()
        if _dops is not None:
            from pathway_tpu.optimize.placement import POLICY

            t0_ns = _time.perf_counter_ns()
            if POLICY.choose("groupby", gb_idx, n):
                try:
                    job = _dops.segment_reduce_dispatch(
                        inverse, diffs, vals, nu
                    )
                except Exception:
                    job = None
        if job is None:
            gdiffs = device.segment_count(inverse, diffs, nu)
            for col in vals:
                deltas.append(
                    None
                    if col is None
                    else device.segment_sum(inverse, col, diffs, nu)
                )
            if _dops is not None:
                POLICY.record(
                    "groupby", gb_idx, False, n,
                    _time.perf_counter_ns() - t0_ns,
                )
        # resolve group ids (creating new groups), all before mutation
        index = self.index
        gis = np.empty(nu, np.int64)
        created: list[int] = []
        for i, raw in enumerate(raws):
            k = self._norm(raw)
            gi = index.get(k)
            if gi is None:
                gi = self.size
                self._grow(gi + 1)
                index[k] = gi
                self.by_raw.append(raw)
                # group id = ref_scalar(*by values) — addressable from
                # pointer_from / ix_ref like the reference (ref_scalar,
                # python_api.rs:3373; group_by_table :2922)
                self.gkeys.append(
                    hash_values(
                        (raw,) if self._single else raw,
                        salt=self.gkey_salt,
                    )
                )
                self.size = gi + 1
                created.append(i)
            gis[i] = gi
        if job is not None:
            # the scatter-adds ran while the dict walk above resolved
            # group ids; materialise their results now
            gdiffs, deltas = job.fetch()
            POLICY.record(
                "groupby", gb_idx, True, n,
                _time.perf_counter_ns() - t0_ns,
            )
        # int64 accumulator headroom: degrade before any mutation
        for ri, delta in enumerate(deltas):
            if delta is None:
                continue
            acc = self.accs[ri]
            if acc.dtype.kind == "i" and delta.dtype.kind != "f":
                amax_acc = int(np.abs(acc[gis]).max(initial=0))
                amax_d = int(np.abs(delta).max(initial=0))
                if amax_acc < 0 or amax_acc + amax_d > (1 << 62):
                    for i in created:  # roll back group creation
                        del index[self._norm(raws[i])]
                    del self.by_raw[self.size - len(created) :]
                    del self.gkeys[self.size - len(created) :]
                    self.size -= len(created)
                    return None
        for ri, delta in enumerate(deltas):
            if delta is None:
                continue
            if delta.dtype.kind == "f" and self.accs[ri].dtype.kind == "i":
                # float contributions arrive: upcast like Python int+float
                self.accs[ri] = self.accs[ri].astype(np.float64)
        old_member = self.member[gis].copy()
        old_accs = [
            self.accs[ri][gis].copy() if d is not None else None
            for ri, d in enumerate(deltas)
        ]
        self.member[gis] = old_member + gdiffs
        for ri, delta in enumerate(deltas):
            if delta is None:
                continue
            acc = self.accs[ri]
            acc[gis] = acc[gis] + delta.astype(acc.dtype, copy=False)
        new_member = self.member[gis]
        for i in np.flatnonzero(new_member <= 0).tolist():
            index.pop(self._norm(raws[i]), None)
        # a group emits only when its VISIBLE row changes (matching the row
        # path's old_row != new_row guard): membership flips always count;
        # count columns change with member, sum columns with the stored acc
        # (post-rounding — a float delta swallowed by rounding emits nothing)
        changed = (old_member > 0) != (new_member > 0)
        for ri, kind in enumerate(self.kinds):
            if kind == ReducerKind.COUNT:
                changed |= old_member != new_member
            else:
                changed |= old_accs[ri] != self.accs[ri][gis]
        m_old = (old_member > 0) & changed
        m_new = (new_member > 0) & changed
        n_out = int(m_old.sum()) + int(m_new.sum())
        if n_out == 0:
            self._maybe_compact()
            return DeltaBatch()
        gkeys = self.gkeys
        by_raw = self.by_raw

        n_by = len(self.by_cols)
        single = self._single

        def block(mask, member_vals, acc_vals):
            sel = np.flatnonzero(mask)
            sel_g = gis[sel].tolist()
            kobjs = list(map(gkeys.__getitem__, sel_g))
            by_vals = list(map(by_raw.__getitem__, sel_g))
            # densify when the by values are cleanly typed, so downstream
            # columnar consumers (hash join, expressions) stay columnar;
            # mixed/exotic values keep the exact object representation
            cols = []
            for j in range(n_by):
                col_vals = (
                    by_vals if single else [t[j] for t in by_vals]
                )
                byv = device._extract(col_vals)
                if byv is None:
                    byv = np.empty(len(col_vals), object)
                    byv[:] = col_vals
                cols.append(byv)
            for ri, kind in enumerate(self.kinds):
                if kind == ReducerKind.COUNT:
                    cols.append(member_vals[sel])
                else:
                    cols.append(acc_vals[ri][sel])
            return kobjs, cols

        ko_old, cols_old = block(m_old, old_member, old_accs)
        new_accs = [
            self.accs[ri][gis] if d is not None else None
            for ri, d in enumerate(deltas)
        ]
        ko_new, cols_new = block(m_new, new_member, new_accs)
        kobjs = ko_old + ko_new

        def cat(a, b):
            # empty placeholders must not promote the other side's dtype,
            # and MISMATCHED dense dtypes (int by-values one commit, str
            # the next) must not silently promote values (int64+<U would
            # stringify the retraction side) — exact objects instead
            if len(a) == 0:
                return b
            if len(b) == 0:
                return a
            if a.dtype == b.dtype:
                return np.concatenate([a, b])
            arr = np.empty(len(a) + len(b), object)
            arr[: len(a)] = a.tolist()
            arr[len(a) :] = b.tolist()
            return arr

        out_cols = [cat(a, b) for a, b in zip(cols_old, cols_new)]
        if ko_old:
            out_diffs = np.concatenate(
                [
                    np.full(len(ko_old), -1, np.int64),
                    np.ones(len(ko_new), np.int64),
                ]
            )
        else:
            # pure-insert commit (bulk load, fresh groups): diffs=None
            # marks the batch insert-only so downstream columnar
            # consumers (the hash join) take it without consolidation
            out_diffs = None
        payload = Columns(
            len(kobjs), out_cols, kobjs=kobjs, diffs=out_diffs
        )
        self._maybe_compact()
        return DeltaBatch.from_columns(
            payload, consolidated=True, insert_only=out_diffs is None
        )

    def _maybe_compact(self) -> None:
        """Reclaim array slots of dead groups (index entry popped, slot
        orphaned). Group-key churn otherwise grows state without bound;
        the row path's dict ``del`` frees dead groups eagerly."""
        live = len(self.index)
        if self.size <= 4096 or self.size <= 2 * live:
            return
        order = sorted(self.index.items(), key=lambda kv: kv[1])
        old_gis = np.fromiter((gi for _k, gi in order), np.int64, live)
        self.by_raw = [self.by_raw[gi] for gi in old_gis]
        self.gkeys = [self.gkeys[gi] for gi in old_gis]
        member = np.zeros(max(self._CAP0, len(self.member) // 2), np.int64)
        while len(member) < live:
            member = np.zeros(len(member) * 2, np.int64)
        member[:live] = self.member[old_gis]
        self.member = member
        for ri, acc in enumerate(self.accs):
            if acc is None:
                continue
            grown = np.zeros(len(member), acc.dtype)
            grown[:live] = acc[old_gis]
            self.accs[ri] = grown
        self.index = {k: i for i, (k, _gi) in enumerate(order)}
        self.size = live

    def materialize(self, node: "GroupbyNode") -> dict[Pointer, list[Any]]:
        """Convert to the row path's dict-of-states form (degradation)."""
        from pathway_tpu.engine.reducers import ReducerKind

        groups: dict[Pointer, list[Any]] = {}
        for k, gi in self.index.items():
            raw = self.by_raw[gi]
            by_vals = (raw,) if self._single else raw
            states = []
            for ri, (reducer, _cols) in enumerate(node.reducers):
                state = reducer.make_state()
                state.count = int(self.member[gi])
                if reducer.kind == ReducerKind.SUM:
                    acc = self.accs[ri][gi]
                    state.acc = (
                        int(acc) if acc.dtype.kind == "i" else float(acc)
                    )
                states.append(state)
            gkey = self.gkeys[gi]
            groups[gkey] = [by_vals, states, int(self.member[gi])]
            node._gkey_cache[(tuple(map(type, by_vals)), by_vals)] = gkey
        return groups


class GroupbyNode(Node):
    """Group-by with engine reducers.

    Output row layout: grouping values, then one value per reducer; the group
    id is ``ref_scalar(*grouping values)`` unless ``set_id`` names a pointer
    column to use directly (reference: group_by_table python_api.rs:2922).

    Single-by-column count/sum groupbys hold their state in
    :class:`_ColumnarGroups` arrays until a batch requires exact row-wise
    semantics; then the state degrades (once) to the dict-of-states form.
    """

    STATE_ATTRS = ("groups",)

    def __init__(
        self,
        scope: "Scope",
        source: Node,
        by_cols: Sequence[int],
        reducers: Sequence[tuple[Reducer, Sequence[int]]],
        set_id: bool = False,
        instance_last: bool = False,
    ) -> None:
        from pathway_tpu.engine.reducers import ReducerKind

        super().__init__(scope, [source], len(by_cols) + len(reducers))
        self.by_cols = list(by_cols)
        self.reducers = list(reducers)
        self.set_id = set_id
        # instance groupbys derive ids like ref_scalar(*vals, instance=i)
        # (salt=b"inst", engine/value.py:377-381) so pointer_from with
        # instance= addresses the groups.
        # COMPAT: earlier builds salted every group id with b"groupby";
        # those keys are unreachable under the current derivation, so an
        # operator snapshot written by such a build must be REJECTED at
        # restore, never loaded — persistence.py guards this with
        # STATE_FORMAT (restoring would strand every persisted group
        # under a key no new row can ever touch).
        self._gkey_salt = b"inst" if instance_last else b""
        # gkey -> [by_vals, [reducer states], membership count]
        self._groups: dict[Pointer, list[Any]] = {}
        self._cg: _ColumnarGroups | None = None
        if (
            not set_id
            and len(by_cols) >= 1
            and all(
                r.kind in (ReducerKind.COUNT, ReducerKind.SUM)
                for r, _c in reducers
            )
        ):
            self._cg = _ColumnarGroups(
                by_cols, reducers, gkey_salt=self._gkey_salt
            )
        # (types, by_vals) -> gkey: a streaming workload touches the same
        # groups commit after commit — the blake2b derivation dominated
        # the incremental-update bench at ~1024 touched groups x 100
        # commits. The cache key carries the value TYPES because dict
        # equality is coarser than the type-tagged digest (True == 1 but
        # hash_values distinguishes them).
        self._gkey_cache: dict[tuple, Pointer] = {}

    @property
    def groups(self) -> dict[Pointer, list[Any]]:
        if self._cg is not None:
            self._groups = self._cg.materialize(self)
            self._cg = None
        return self._groups

    @groups.setter
    def groups(self, value: dict[Pointer, list[Any]]) -> None:
        self._groups = value
        self._cg = None

    def op_state(self) -> dict:
        # snapshots (operator persistence) must not degrade the columnar
        # state: materialise a dict VIEW for the snapshot, keep _cg live
        state = {"current": dict(self.current)}
        state["groups"] = (
            self._cg.materialize(self) if self._cg is not None else self._groups
        )
        return state

    def _group_key(self, by_vals: tuple) -> Pointer:
        if self.set_id:
            assert len(by_vals) == 1 and isinstance(by_vals[0], Pointer)
            return by_vals[0]
        ck = (tuple(map(type, by_vals)), by_vals)
        try:
            gkey = self._gkey_cache.get(ck)
        except TypeError:  # unhashable by-values: derive directly
            return hash_values(by_vals, salt=self._gkey_salt)
        if gkey is None:
            gkey = hash_values(by_vals, salt=self._gkey_salt)
            self._gkey_cache[ck] = gkey
        return gkey

    def _group_row(self, entry: list[Any]) -> tuple:
        by_vals, states, _count = entry
        vals = []
        for (reducer, _cols), state in zip(self.reducers, states):
            vals.append(reducer.compute(state))
        return tuple(by_vals) + tuple(vals)

    def _process_columnar(self, batch: DeltaBatch) -> DeltaBatch | None:
        """Vectorized path for count/sum groupbys over clean by columns:
        per-row work collapses to factorization + segment reductions
        (engine/device.py), leaving only per-group Python. Falls back (None)
        whenever semantics would differ from the row-wise loop."""
        from pathway_tpu.engine import device
        from pathway_tpu.engine.reducers import ReducerKind

        if self.set_id or len(self.by_cols) < 1:
            return None
        for reducer, cols in self.reducers:
            if reducer.kind not in (ReducerKind.COUNT, ReducerKind.SUM):
                return None
        sum_col_idx = [
            cols[0] if r.kind == ReducerKind.SUM else -1
            for r, cols in self.reducers
        ]
        got = _groupby_batch_arrays(batch, self.by_cols, sum_col_idx)
        if got is None:
            return None
        bys, diffs, vals = got
        n = len(bys[0])
        dmax = int(np.abs(diffs).max()) if n else 0
        if dmax < 0:  # abs(INT64_MIN) wraps
            return None
        sum_arrays: dict[int, Any] = {}
        for ri, col in enumerate(vals):
            if col is None:
                continue
            if device.int_sum_overflow_risk(col, n, dmax):
                return None
            sum_arrays[ri] = col
        uniques, inverse = _factorize_bys(bys)
        n_groups = len(uniques)
        gdiffs = device.segment_count(inverse, diffs, n_groups)
        aggs: list[Any] = []
        for ri, (reducer, cols) in enumerate(self.reducers):
            if reducer.kind == ReducerKind.COUNT:
                aggs.append(None)
            else:
                aggs.append(
                    device.segment_sum(
                        inverse, sum_arrays[ri], diffs, n_groups
                    )
                )
        out = DeltaBatch()
        for gi, by_vals in enumerate(uniques):
            gkey = self._group_key(by_vals)
            entry = self.groups.get(gkey)
            old_row = self._group_row(entry) if entry is not None else None
            if entry is None:
                entry = [
                    by_vals,
                    [reducer.make_state() for reducer, _c in self.reducers],
                    0,
                ]
                self.groups[gkey] = entry
            gdiff = int(gdiffs[gi])
            entry[2] += gdiff
            for ri, ((reducer, _cols), state) in enumerate(
                zip(self.reducers, entry[1])
            ):
                state.count += gdiff
                if reducer.kind == ReducerKind.SUM:
                    delta = aggs[ri][gi].item()
                    state.acc = delta if state.acc is None else state.acc + delta
            new_row: tuple | None = None
            if entry[2] <= 0:
                del self.groups[gkey]
                self._gkey_cache.pop(
                    (tuple(map(type, by_vals)), by_vals), None
                )
            else:
                new_row = self._group_row(entry)
            if old_row is not None and old_row != new_row:
                out.append(gkey, old_row, -1)
            if new_row is not None and old_row != new_row:
                out.append(gkey, new_row, 1)
        return out.consolidate()

    def process(self, time: int) -> DeltaBatch:
        if self._cg is not None:
            # segment sums are diff-linear: duplicate / net-zero entries
            # contribute exactly their diff, so skip consolidation
            batch = self.take_raw(0)
            out = self._cg.process_batch(batch, self)
            if out is not None:
                return out
            # this batch needs exact row semantics: degrade the columnar
            # state to dict-of-states (once) and fall through
            self.groups  # noqa: B018 — property materialises + clears _cg
            batch = batch.consolidate()
        else:
            batch = self.take(0)
        if len(batch) >= VECTOR_THRESHOLD:
            fast = self._process_columnar(batch)
            if fast is not None:
                return fast
        touched: dict[Pointer, tuple | None] = {}
        for key, row, diff in batch:
            by_vals = tuple(row[c] for c in self.by_cols)
            if any(is_error(v) for v in by_vals):
                self.report(key, "error value in groupby key")
                continue
            gkey = self._group_key(by_vals)
            entry = self.groups.get(gkey)
            if gkey not in touched:
                touched[gkey] = self._group_row(entry) if entry is not None else None
            if entry is None:
                entry = [
                    by_vals,
                    [reducer.make_state() for reducer, _c in self.reducers],
                    0,
                ]
                self.groups[gkey] = entry
            entry[2] += diff
            for (reducer, cols), state in zip(self.reducers, entry[1]):
                args = tuple(row[c] for c in cols)
                reducer.update(state, args, diff, time)
        out = DeltaBatch()
        for gkey, old_row in touched.items():
            entry = self.groups.get(gkey)
            new_row: tuple | None = None
            if entry is not None:
                if entry[2] <= 0:
                    del self.groups[gkey]
                    bv = tuple(entry[0])
                    self._gkey_cache.pop((tuple(map(type, bv)), bv), None)
                else:
                    new_row = self._group_row(entry)
            if old_row is not None and old_row != new_row:
                out.append(gkey, old_row, -1)
            if new_row is not None and old_row != new_row:
                out.append(gkey, new_row, 1)
        return out.consolidate()


class DeduplicateNode(Node):
    """Keep one accepted row per instance (reference: deduplicate :2943).

    ``acceptor(new_value, old_value) -> bool`` decides whether a newly
    arriving row replaces the current one.
    """

    STATE_ATTRS = ("accepted",)

    def __init__(
        self,
        scope: "Scope",
        source: Node,
        value_col: int,
        instance_cols: Sequence[int],
        acceptor: Callable[[Any, Any], bool],
    ) -> None:
        super().__init__(scope, [source], source.arity)
        self.value_col = value_col
        self.instance_cols = list(instance_cols)
        self.acceptor = acceptor
        self.accepted: dict[Pointer, tuple] = {}  # gkey -> row

    def process(self, time: int) -> DeltaBatch:
        batch = self.take(0)
        out = DeltaBatch()
        for key, row, diff in batch:
            inst = tuple(row[c] for c in self.instance_cols)
            gkey = hash_values(inst, salt=b"dedup")
            prev = self.accepted.get(gkey)
            if diff > 0:
                new_val = row[self.value_col]
                if is_error(new_val):
                    self.report(key, "error value in deduplicate")
                    continue
                if prev is None:
                    accept = True
                else:
                    try:
                        accept = bool(self.acceptor(new_val, prev[self.value_col]))
                    except Exception as e:  # noqa: BLE001
                        self.report(key, f"error in deduplicate acceptor: {e}")
                        continue
                if accept:
                    if prev is not None:
                        out.append(gkey, prev, -1)
                    self.accepted[gkey] = row
                    out.append(gkey, row, 1)
            else:
                if prev is not None and not rows_differ(prev, row):
                    out.append(gkey, prev, -1)
                    del self.accepted[gkey]
        return out.consolidate()


class FlattenNode(Node):
    """Explode a sequence column into one row per element; with
    ``with_origin`` the source row id is appended as a final column
    (reference flatten origin_id)."""

    def __init__(
        self,
        scope: "Scope",
        source: Node,
        flat_col: int,
        with_origin: bool = False,
    ) -> None:
        super().__init__(scope, [source], source.arity + (1 if with_origin else 0))
        self.flat_col = flat_col
        self.with_origin = with_origin

    def _explode(self, key: Pointer, row: tuple) -> list[tuple[Pointer, tuple]]:
        value = row[self.flat_col]
        if is_error(value):
            self.report(key, "error value in flatten column")
            return []
        if value is None:
            return []
        try:
            elements = list(value)
        except TypeError:
            self.report(key, f"cannot flatten non-sequence {value!r}")
            return []
        out = []
        for i, element in enumerate(elements):
            new_key = hash_values((key, i), salt=b"flatten")
            new_row = row[: self.flat_col] + (element,) + row[self.flat_col + 1 :]
            if self.with_origin:
                new_row = new_row + (key,)
            out.append((new_key, new_row))
        return out

    def process(self, time: int) -> DeltaBatch:
        batch = self.take(0)
        out = DeltaBatch()
        for key, row, diff in batch:
            for new_key, new_row in self._explode(key, row):
                out.append(new_key, new_row, diff)
        return out.consolidate()


class SortNode(Node):
    """Maintains prev/next pointers per instance, sorted by a key column.

    Output row: ``(prev: Pointer|None, next: Pointer|None)`` keyed by the
    source row id (reference: add_prev_next_pointers,
    src/engine/dataflow/operators/prev_next.rs:770 — here recomputed per
    affected instance group, which preserves the output contract).
    """

    STATE_ATTRS = ("members",)

    def __init__(
        self, scope: "Scope", source: Node, key_col: int, instance_col: int | None
    ) -> None:
        super().__init__(scope, [source], 2)
        self.key_col = key_col
        self.instance_col = instance_col
        self.members: dict[Any, dict[Pointer, Any]] = {}  # instance -> {key: sortval}

    def _instance(self, row: tuple) -> Any:
        if self.instance_col is None:
            return None
        v = row[self.instance_col]
        try:
            hash(v)
        except TypeError:
            v = repr(v)
        return v

    def _ordered(self, inst: Any) -> list[Pointer]:
        rows = self.members.get(inst, {})
        items = list(rows.items())
        try:
            # None sorts first; natural order within non-None values
            items.sort(key=lambda kv: (kv[1] is not None, kv[1], int(kv[0]))
                       if kv[1] is not None else (False, 0, int(kv[0])))
        except TypeError:
            # incomparable mix: deterministic fallback by type name + repr
            items.sort(
                key=lambda kv: (
                    kv[1] is not None,
                    type(kv[1]).__name__,
                    repr(kv[1]),
                    int(kv[0]),
                )
            )
        return [k for k, _v in items]

    def _local(self, inst: Any) -> dict[Pointer, tuple]:
        ordered = self._ordered(inst)
        out: dict[Pointer, tuple] = {}
        for i, k in enumerate(ordered):
            prev = ordered[i - 1] if i > 0 else None
            nxt = ordered[i + 1] if i < len(ordered) - 1 else None
            out[k] = (prev, nxt)
        return out

    def process(self, time: int) -> DeltaBatch:
        batch = self.take(0)
        old: dict[Any, dict[Pointer, tuple]] = {}
        for key, row, diff in batch:
            inst = self._instance(row)
            if inst not in old:
                old[inst] = self._local(inst)
        for key, row, diff in batch:
            inst = self._instance(row)
            group = self.members.setdefault(inst, {})
            if diff > 0:
                group[key] = row[self.key_col]
            else:
                group.pop(key, None)
                if not group:
                    self.members.pop(inst, None)
        out = DeltaBatch()
        for inst, old_rows in old.items():
            new_rows = self._local(inst)
            for k, r in old_rows.items():
                if rows_differ(new_rows.get(k), r):
                    out.append(k, r, -1)
            for k, r in new_rows.items():
                if rows_differ(old_rows.get(k), r):
                    out.append(k, r, 1)
        return out.consolidate()


class IxNode(InputMirrors, Node):
    """Pointer-lookup join: for each input row, fetch the source row its
    key column points to (reference: ix_table python_api.rs:2963).
    """

    STATE_ATTRS = ("forward", "reverse", "_mirrors")

    def __init__(
        self,
        scope: "Scope",
        keys_table: Node,
        source_table: Node,
        key_col: int,
        optional: bool = False,
        strict: bool = True,
    ) -> None:
        super().__init__(scope, [keys_table, source_table], source_table.arity)
        self.key_col = key_col
        self.optional = optional
        self.strict = strict
        self.forward: dict[Pointer, Pointer] = {}  # input key -> source key
        self.reverse: dict[Pointer, set[Pointer]] = {}  # source key -> input keys
        self._init_mirrors()

    def _lookup(self, key: Pointer, skey: Pointer | None) -> tuple | None:
        if skey is None:
            if self.optional:
                return (None,) * self.arity
            self.report(key, "ix: key is None and optional=False")
            return None
        src = self._input_state(1).get(skey)
        if src is None:
            if self.strict:
                self.report(key, f"ix: missing key {skey!r}")
                return None
            return (None,) * self.arity
        return src

    def process(self, time: int) -> DeltaBatch:
        keys_batch = self.take(0)
        source_batch = self.take(1)
        self._absorb(1, source_batch)
        out = DeltaBatch()
        # Source-side changes: re-emit rows for affected input keys
        affected_src: set[Pointer] = {key for key, _r, _d in source_batch}
        handled: set[Pointer] = set()
        for key, row, diff in keys_batch:
            handled.add(key)
        state = self.current  # hoisted: drains lazy state once
        for skey in affected_src:
            for ikey in self.reverse.get(skey, set()) - handled:
                old = state.get(ikey)
                new = self._lookup(ikey, self.forward.get(ikey))
                if old is not None and rows_differ(old, new):
                    out.append(ikey, old, -1)
                if new is not None and rows_differ(old, new):
                    out.append(ikey, new, 1)
        # Input-side changes
        for key, row, diff in keys_batch:
            if diff < 0:
                if key in state:
                    out.append(key, state[key], -1)
                skey = self.forward.pop(key, None)
                if skey is not None:
                    self.reverse.get(skey, set()).discard(key)
                continue
            skey = row[self.key_col]
            if is_error(skey):
                self.report(key, "error value in ix key")
                continue
            if skey is not None and not isinstance(skey, Pointer):
                self.report(key, f"ix key must be a pointer, got {skey!r}")
                continue
            if key in state:
                out.append(key, state[key], -1)
            if skey is not None:
                self.forward[key] = skey
                self.reverse.setdefault(skey, set()).add(key)
            new = self._lookup(key, skey)
            if new is not None:
                out.append(key, new, 1)
        return out.consolidate()


class UpdateRowsNode(InputMirrors, Node):
    """``orig.update_rows(updates)`` — updates win per key; union of universes."""

    STATE_ATTRS = ("_mirrors",)

    def __init__(self, scope: "Scope", orig: Node, updates: Node) -> None:
        assert orig.arity == updates.arity
        super().__init__(scope, [orig, updates], orig.arity)
        self._init_mirrors()

    def _effective(self, key: Pointer) -> tuple | None:
        upd = self._input_state(1).get(key)
        if upd is not None:
            return upd
        return self._input_state(0).get(key)

    def process(self, time: int) -> DeltaBatch:
        affected: set[Pointer] = set()
        for port in (0, 1):
            batch = self.take(port)
            self._absorb(port, batch)
            for key, _row, _diff in batch:
                affected.add(key)
        out = DeltaBatch()
        state = self.current  # hoisted: drains lazy state once
        for key in affected:
            old = state.get(key)
            new = self._effective(key)
            if old is not None and rows_differ(old, new):
                out.append(key, old, -1)
            if new is not None and rows_differ(old, new):
                out.append(key, new, 1)
        return out


class UpdateCellsNode(InputMirrors, Node):
    """``orig.update_cells(updates)`` — override selected columns per key.

    ``update_cols[i]`` gives, for each output column, the column index in the
    updates table or -1 to keep the original value.
    """

    STATE_ATTRS = ("_mirrors",)

    def __init__(
        self, scope: "Scope", orig: Node, updates: Node, update_cols: Sequence[int]
    ) -> None:
        super().__init__(scope, [orig, updates], orig.arity)
        self.update_cols = list(update_cols)
        self._init_mirrors()

    def _effective(self, key: Pointer) -> tuple | None:
        orig = self._input_state(0).get(key)
        if orig is None:
            return None
        upd = self._input_state(1).get(key)
        if upd is None:
            return orig
        return tuple(
            upd[uc] if uc >= 0 else orig[i] for i, uc in enumerate(self.update_cols)
        )

    def process(self, time: int) -> DeltaBatch:
        affected: set[Pointer] = set()
        for port in (0, 1):
            batch = self.take(port)
            self._absorb(port, batch)
            for key, _row, _diff in batch:
                affected.add(key)
        out = DeltaBatch()
        state = self.current  # hoisted: drains lazy state once
        for key in affected:
            old = state.get(key)
            new = self._effective(key)
            if old is not None and rows_differ(old, new):
                out.append(key, old, -1)
            if new is not None and rows_differ(old, new):
                out.append(key, new, 1)
        return out


class SubscribeNode(Node):
    """Sink: per-row callbacks + time/end notifications (subscribe_table)."""

    def __init__(
        self,
        scope: "Scope",
        source: Node,
        on_change: Callable[[Pointer, tuple, int, int], None] | None = None,
        on_time_end: Callable[[int], None] | None = None,
        on_end: Callable[[], None] | None = None,
        skip_errors: bool = True,
    ) -> None:
        super().__init__(scope, [source], source.arity)
        self._on_change = on_change
        self._on_time_end = on_time_end
        self._on_end = on_end
        self.skip_errors = skip_errors
        self._saw_data = False

    def process(self, time: int) -> DeltaBatch:
        batch = self.take(0)
        rows = 0
        retractions = 0
        for key, row, diff in batch:
            if self.skip_errors and any(is_error(v) for v in row):
                self.report(key, "error value in output row")
                continue
            self._saw_data = True
            rows += 1
            if diff < 0:
                retractions += 1
            if self._on_change is not None:
                self._on_change(key, row, time, diff)
        if rows:
            _OUTPUT_ROWS.inc(rows)
            tr = _tracing.current()
            if tr is not None:
                tr.note_sink(rows)
        if retractions:
            _metrics.FLIGHT.record(
                "retractions", time=time, count=retractions, sink=self.index
            )
        return batch

    def on_time_end(self, time: int) -> None:
        if self._on_time_end is not None:
            self._on_time_end(time)

    def close(self) -> None:
        # the user's on_end ("stream finished") fires here — after the
        # settlement commit — so buffer-flush rows injected by upstream
        # on_end hooks were already delivered through on_change
        if self._on_end is not None:
            self._on_end()


class ErrorLogNode(Node):
    """Error log as an engine table of (message,) rows
    (reference: error_log dataflow.rs:3980, pw.global_error_log()).
    """

    def __init__(self, scope: "Scope") -> None:
        super().__init__(scope, [], 1)
        self._counter = itertools.count()
        self.buffered: list[tuple[Pointer, tuple, int]] = []

    def log(self, message: str) -> None:
        key = hash_values((next(self._counter), message), salt=b"errlog")
        self.buffered.append((key, (message,), 1))
        _metrics.FLIGHT.record("error", message=message)

    def flush_buffer(self) -> DeltaBatch | None:
        if not self.buffered:
            return None
        out = DeltaBatch(self.buffered)
        self.buffered = []
        return out

    def process(self, time: int) -> DeltaBatch:
        return self.take(0)


def emit_local_group_diffs(
    out: DeltaBatch,
    old_groups: dict,
    local_fn: Callable[[Any], dict],
) -> None:
    """Shared incremental-recompute tail: for each touched group, diff the
    snapshot taken before the batch against the recomputed local output and
    emit retract/insert pairs. Used by the group-local operators (joins,
    sort, sessions, temporal joins)."""
    for inst, old_rows in old_groups.items():
        new_rows = local_fn(inst)
        for k, r in old_rows.items():
            if rows_differ(new_rows.get(k), r):
                out.append(k, r, -1)
        for k, r in new_rows.items():
            if rows_differ(old_rows.get(k), r):
                out.append(k, r, 1)


class Scope:
    """The engine graph builder + owner of all nodes.

    The Python framework lowers its ParseGraph onto this API; it mirrors the
    reference's `Scope` pyclass (src/python_api.rs:2248) with tables as
    node handles and columns as tuple positions.
    """

    def __init__(self) -> None:
        self.nodes: list[Node] = []
        self.error_log_default = ErrorLogNode(self)
        self._error_log_stack: list[ErrorLogNode] = [self.error_log_default]
        self.worker_index = 0
        self.worker_count = 1
        #: set by the sharded/distributed schedulers: replica node state
        #: (`current`) then holds only a key shard, so state-peeking
        #: operators (zip/ix/update/iterate) switch to own input mirrors
        self.sharded = False

    # -- error plumbing -----------------------------------------------------

    def report_error(self, node: Node, key: Pointer | None, message: str) -> None:
        trace = f" at {node.trace}" if node.trace else ""
        # nodes built inside `with pw.local_error_log()` carry their own log
        log = getattr(node, "error_log", None) or self._error_log_stack[-1]
        log.log(f"{node.name}{trace}: {message}")

    def error_log(self) -> ErrorLogNode:
        return ErrorLogNode(self)

    def push_error_log(self, log: ErrorLogNode) -> None:
        self._error_log_stack.append(log)

    def pop_error_log(self) -> None:
        self._error_log_stack.pop()

    # -- table constructors -------------------------------------------------

    def empty_table(self, arity: int) -> Node:
        return StaticSource(self, [], arity)

    def static_table(self, rows: Iterable[tuple[Pointer, tuple]], arity: int) -> Node:
        return StaticSource(self, rows, arity)

    def input_session(self, arity: int, upsert: bool = False) -> InputSession:
        return InputSession(self, arity, upsert=upsert)

    # -- operators ----------------------------------------------------------

    def expression_table(
        self, table: Node, expressions: Sequence[EngineExpression]
    ) -> Node:
        return ExpressionNode(self, table, expressions)

    def zip_tables(self, tables: Sequence[Node]) -> Node:
        if len(tables) == 1:
            return tables[0]
        return ZipNode(self, tables)

    def filter_table(self, table: Node, condition_col: int) -> Node:
        return FilterNode(self, table, condition_col)

    def batch_apply_table(
        self,
        table: Node,
        rows_fn: Callable[[list], list],
        arg_cols: Sequence[int],
        propagate_none: bool = False,
    ) -> Node:
        return BatchApplyNode(self, table, rows_fn, arg_cols, propagate_none)

    def concat_tables(self, tables: Sequence[Node]) -> Node:
        return ConcatNode(self, tables)

    def reindex_table(self, table: Node, key_col: int) -> Node:
        return ReindexNode(self, table, key_col)

    def intersect_tables(self, table: Node, others: Sequence[Node]) -> Node:
        return KeyFilterNode(self, table, others, "intersect")

    def subtract_table(self, table: Node, other: Node) -> Node:
        return KeyFilterNode(self, table, [other], "subtract")

    def restrict_table(self, table: Node, universe: Node) -> Node:
        return KeyFilterNode(self, table, [universe], "restrict")

    def override_table_universe(self, table: Node, universe: Node) -> Node:
        return OverrideUniverseNode(self, table)

    def join_tables(
        self,
        left: Node,
        right: Node,
        left_on: Sequence[int],
        right_on: Sequence[int],
        kind: str = JoinKind.INNER,
        id_from_left: bool = False,
        id_spec: tuple | None = None,
    ) -> Node:
        return JoinNode(
            self,
            left,
            right,
            left_on,
            right_on,
            kind=kind,
            id_from_left=id_from_left,
            id_spec=id_spec,
        )

    def group_by_table(
        self,
        table: Node,
        by_cols: Sequence[int],
        reducers: Sequence[tuple[Reducer, Sequence[int]]],
        set_id: bool = False,
        instance_last: bool = False,
    ) -> Node:
        return GroupbyNode(
            self,
            table,
            by_cols,
            reducers,
            set_id=set_id,
            instance_last=instance_last,
        )

    def deduplicate(
        self,
        table: Node,
        value_col: int,
        instance_cols: Sequence[int],
        acceptor: Callable[[Any, Any], bool],
    ) -> Node:
        return DeduplicateNode(self, table, value_col, instance_cols, acceptor)

    def recompute_table(
        self, sources: Sequence[Node], compute: Callable[[list], dict], arity: int
    ) -> Node:
        return RecomputeNode(self, sources, compute, arity)

    def export_table(
        self, table: Node, handle: "ExportedTable | None" = None
    ) -> "ExportedTable":
        """Reference graph.rs:609 export_table: subscribe the node into a
        cross-graph handle (pass ``handle`` to fill a pre-created one)."""
        exported = handle if handle is not None else ExportedTable(table.arity)
        self.subscribe_table(
            table,
            on_change=exported._on_change,
            on_end=exported._on_end,
        )
        return exported

    def flatten_table(
        self, table: Node, flat_col: int, with_origin: bool = False
    ) -> Node:
        return FlattenNode(self, table, flat_col, with_origin=with_origin)

    def sort_table(self, table: Node, key_col: int, instance_col: int | None) -> Node:
        return SortNode(self, table, key_col, instance_col)

    def ix_table(
        self,
        keys_table: Node,
        source_table: Node,
        key_col: int,
        optional: bool = False,
        strict: bool = True,
    ) -> Node:
        return IxNode(self, keys_table, source_table, key_col, optional, strict)

    def update_rows_table(self, orig: Node, updates: Node) -> Node:
        return UpdateRowsNode(self, orig, updates)

    def update_cells_table(
        self, orig: Node, updates: Node, update_cols: Sequence[int]
    ) -> Node:
        return UpdateCellsNode(self, orig, updates, update_cols)

    def subscribe_table(
        self,
        table: Node,
        on_change: Callable[[Pointer, tuple, int, int], None] | None = None,
        on_time_end: Callable[[int], None] | None = None,
        on_end: Callable[[], None] | None = None,
        skip_errors: bool = True,
    ) -> SubscribeNode:
        return SubscribeNode(
            self, table, on_change, on_time_end, on_end, skip_errors=skip_errors
        )

    def remove_errors_from_table(self, table: Node) -> Node:
        return _RemoveErrorsNode(self, table)

    # -- execution ----------------------------------------------------------

    def run(
        self,
        strict: bool = False,
        probe: bool = False,
        optimize: bool = True,
    ) -> "Scheduler":
        """Build-and-go convenience: pump every static source through one
        commit and finish.  ``strict=True`` first runs the pre-execution
        static analyzer (pathway_tpu.analysis) and raises
        ``AnalysisError`` on any error-severity finding — the graph is
        rejected before any state is created.  ``optimize=True`` (default)
        runs the pre-execution graph rewriter (pathway_tpu.optimize);
        ``PATHWAY_TPU_OPTIMIZE=0`` is the environment escape hatch."""
        if strict:
            from pathway_tpu.analysis import check_strict

            check_strict(self)
        scheduler = Scheduler(self, probe=probe, optimize=optimize)
        scheduler.run_static()
        return scheduler


class _RemoveErrorsNode(Node):
    def __init__(self, scope: Scope, source: Node) -> None:
        super().__init__(scope, [source], source.arity)

    def process(self, time: int) -> DeltaBatch:
        batch = self.take(0)
        out = DeltaBatch()
        state = self.current  # hoisted: drains lazy state once
        for key, row, diff in batch:
            if diff < 0:
                if key in state:
                    out.append(key, state[key], -1)
                continue
            if any(is_error(v) for v in row):
                continue
            out.append(key, row, diff)
        return out


class OperatorStats:
    """Per-operator probe counters (reference: OperatorStats
    graph.rs:500-542 + Prober dataflow.rs:671-798)."""

    __slots__ = ("insertions", "deletions", "batches", "time_spent", "last_time")

    def __init__(self) -> None:
        self.insertions = 0
        self.deletions = 0
        self.batches = 0
        self.time_spent = 0.0  # seconds inside process()
        self.last_time: int | None = None  # last commit that touched this op

    def snapshot(self) -> dict:
        return {
            "insertions": self.insertions,
            "deletions": self.deletions,
            "batches": self.batches,
            "time_spent": self.time_spent,
            "last_time": self.last_time,
        }


class Scheduler:
    """Topological commit-batch pump (replaces timely's worker loop,
    reference: dataflow.rs:5769-5822). All deltas at one logical time are
    processed as a unit; ``propagate`` loops until quiescent so same-time
    feedback (error logs) settles within the commit.

    ``probe=True`` collects per-operator stats into ``self.stats``
    (node index → OperatorStats), feeding the monitoring dashboard and the
    Prometheus endpoint.
    """

    def __init__(
        self, scope: Scope, probe: bool = False, optimize: bool = True
    ) -> None:
        if optimize:
            from pathway_tpu.optimize import optimize_scopes

            # single-worker: no exchanges to elide, but fusion/pushdown
            # still apply (skips itself under PATHWAY_TPU_OPTIMIZE=0 and
            # in analyze mode; idempotent per scope)
            optimize_scopes([scope])
        self.scope = scope
        self.time = 0
        self.probe = probe
        self.stats: dict[int, OperatorStats] = {}
        if probe:
            self._queue_gauge = _metrics.REGISTRY.gauge(
                "pathway_queue_depth",
                "operators with pending delta batches (backpressure)",
            )

    def _stats_of(self, node: Node) -> OperatorStats:
        st = self.stats.get(node.index)
        if st is None:
            st = self.stats[node.index] = OperatorStats()
        return st

    def propagate(self, time: int) -> None:
        scope = self.scope
        probe = self.probe
        trace = _tracing.current()
        if probe or trace is not None:
            import time as _walltime
        while True:
            dirty = [n for n in scope.nodes if n.has_pending()]
            if probe:
                self._queue_gauge.value = float(len(dirty))
            if not dirty:
                # flush error-log buffers; may create new pending work
                flushed = False
                for node in scope.nodes:
                    if isinstance(node, ErrorLogNode):
                        batch = node.flush_buffer()
                        if batch:
                            node.push(0, batch)
                            flushed = True
                if not flushed:
                    break
                continue
            for node in scope.nodes:
                if not node.has_pending():
                    continue
                if probe or trace is not None:
                    t0 = _walltime.perf_counter()
                out = node.process(time)
                if out is None:
                    out = DeltaBatch()
                # no eager consolidation: consumers consolidate in take()
                # (cached), lazy state drain consolidates before applying
                node._defer_state(out)
                if trace is not None:
                    t1 = _walltime.perf_counter()
                    trace.span(
                        getattr(node, "name", None)
                        or type(node).__name__,
                        "sink" if isinstance(node, SubscribeNode) else "op",
                        t0,
                        t1,
                        node=node.index,
                    )
                if probe:
                    st = self._stats_of(node)
                    st.time_spent += _walltime.perf_counter() - t0
                    st.batches += 1
                    st.last_time = time
                    cols = out.columns
                    if cols is not None:
                        # count from the diff vector — don't materialise
                        # rows just for monitoring
                        if cols.diffs is None:
                            st.insertions += cols.n
                        else:
                            pos = int((cols.diffs > 0).sum())
                            st.insertions += pos
                            st.deletions += cols.n - pos
                    else:
                        # consolidate for counting: raw batches may carry
                        # net-zero churn that monitoring should not report
                        for _k, _r, d in out.consolidate():
                            if d > 0:
                                st.insertions += 1
                            else:
                                st.deletions += 1
                if out:
                    for consumer, port in node.consumers:
                        consumer.push(port, out)
        for node in scope.nodes:
            node.on_time_end(time)
        from pathway_tpu.engine import device_pipeline

        device_pipeline.commit_boundary(time)

    def _end_nodes(self) -> None:
        """Run on_end hooks; they may inject final batches (buffer flush) —
        propagate those as one more commit, then tear sinks down."""
        for node in self.scope.nodes:
            node.on_end()
        if any(n.has_pending() for n in self.scope.nodes):
            self.propagate(self.time)
            self.time += 1
        from pathway_tpu.engine import device_pipeline

        device_pipeline.drain()
        for node in self.scope.nodes:
            node.close()

    def _analysis_intercept(self) -> bool:
        """Under ``cli analyze`` (PATHWAY_TPU_ANALYZE=1) the scheduler
        records the built graph for static analysis and skips execution."""
        from pathway_tpu.analysis import runtime as _analysis_runtime

        return _analysis_runtime.intercept(self.scope)

    def run_static(self) -> None:
        """Batch mode: all static sources at time 0, one commit, then end."""
        if self._analysis_intercept():
            self.time = 1
            return
        for node in self.scope.nodes:
            if isinstance(node, StaticSource):
                batch = node.initial_batch()
                if batch:
                    node.push(0, batch)
        self.propagate(0)
        self.time = 1
        self._end_nodes()

    def commit(self) -> int:
        """Streaming mode: flush all input sessions as one commit."""
        if self._analysis_intercept():
            time = self.time
            self.time += 1
            return time
        for node in self.scope.nodes:
            if isinstance(node, StaticSource):
                batch = node.initial_batch()
                if batch:
                    node.push(0, batch)
            elif isinstance(node, InputSession):
                batch = node.flush()
                if batch:
                    node.push(0, batch)
        time = self.time
        self.propagate(time)
        self.time += 1
        return time

    def finish(self) -> None:
        if self._analysis_intercept():
            return
        self.commit()
        self._end_nodes()


class RecomputeNode(Node):
    """Whole-recompute operator: ``compute(input_states) -> {key: row}``,
    diffed against the previous output. Backs row transformers
    (reference complex_columns.rs — demand-driven there, local recompute
    here, same results)."""

    STATE_ATTRS = ("_input_states",)

    def __init__(
        self,
        scope: "Scope",
        sources: Sequence[Node],
        compute: Callable[[list], dict],
        arity: int,
    ) -> None:
        super().__init__(scope, list(sources), arity)
        self.compute = compute
        # own mirror of each input built from received batches — under
        # sharded execution the local replicas' `current` only holds one
        # shard, while this node (pinned to worker 0) sees every batch
        self._input_states: list[dict[Pointer, tuple]] = [
            {} for _ in sources
        ]

    def process(self, time: int) -> DeltaBatch:
        for port in range(len(self.inputs)):
            apply_batch_to_state(self._input_states[port], self.take(port))
        try:
            new = self.compute(self._input_states)
        except Exception as e:  # noqa: BLE001
            self.report(None, f"row transformer error: {e!r}")
            return DeltaBatch()
        out = DeltaBatch()
        state = self.current  # hoisted: drains lazy state once
        for key, row in state.items():
            if rows_differ(new.get(key), row):
                out.append(key, row, -1)
        for key, row in new.items():
            if rows_differ(state.get(key), row):
                out.append(key, row, 1)
        return out.consolidate()


class ExportedTable:
    """Cross-graph table handle (reference: ExportedTable graph.rs:609,
    export.rs): a live snapshot plus update callbacks, consumable by
    ``import_table`` in another graph."""

    def __init__(self, arity: int) -> None:
        import threading

        self.arity = arity
        self.current: dict[Pointer, tuple] = {}
        self._callbacks: list = []
        self.finished = False
        self._lock = threading.Lock()

    # producer side --------------------------------------------------------
    def _on_change(self, key: Pointer, row: tuple, time: int, diff: int) -> None:
        with self._lock:
            if diff > 0:
                self.current[key] = row
            else:
                self.current.pop(key, None)
            callbacks = list(self._callbacks)
        for cb in callbacks:
            cb(key, row, time, diff)

    def _on_end(self) -> None:
        with self._lock:
            self.finished = True
            callbacks = list(self._callbacks)
        for cb in callbacks:
            cb(None, None, None, 0)

    # consumer side --------------------------------------------------------
    def snapshot(self) -> dict[Pointer, tuple]:
        with self._lock:
            return dict(self.current)

    def subscribe(self, callback) -> None:
        with self._lock:
            self._callbacks.append(callback)

    def subscribe_with_snapshot(self, callback) -> tuple[dict, bool]:
        """Atomically: register the callback and return (snapshot,
        finished). No update committed after the snapshot can be missed,
        and none in the snapshot is re-delivered."""
        with self._lock:
            self._callbacks.append(callback)
            return dict(self.current), self.finished

    def unsubscribe(self, callback) -> None:
        with self._lock:
            if callback in self._callbacks:
                self._callbacks.remove(callback)
