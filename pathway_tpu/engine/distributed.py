"""Multi-process execution: a TCP exchange mesh between worker processes.

This is the DCN leg of the worker model (reference: timely
``CommunicationConfig::Cluster`` built in src/engine/dataflow/config.rs:72-86
from PATHWAY_PROCESSES/PATHWAY_PROCESS_ID/PATHWAY_FIRST_PORT, launched by
`pathway spawn`, python/pathway/cli.py:93-107; transport = vendored timely
communication: TCP sockets + progress gossip, SURVEY §2.10).

Design (TPU-first, not a timely translation):

- Every process runs the IDENTICAL program and builds the identical graph
  (the reference re-executes the Python logic per worker,
  python_api.rs:3329). Total workers = processes x threads; worker ``w``
  lives on process ``w // threads``. Partitioning seams are shared with the
  in-process exchange (engine/sharded.py `partitioner`).
- Process 0 is the coordinator: it owns connector drivers (inputs read on
  one worker and reshard, reference dataflow.rs:3492) and all sinks
  (single-threaded sinks, data_storage.rs:611). It drives commits by
  broadcasting control frames.
- In place of timely's asynchronous progress gossip, a commit settles with
  *synchronous exchange rounds*: each round every process drains its local
  operators to quiescence, then swaps one frame with every peer carrying
  (busy-bit, deliveries). A commit is done after a round in which no
  process was busy and nothing was exchanged — at that point nothing can
  be in flight, so this is an exact distributed-quiescence test. The round
  barrier is the host-side analog of the jit step boundary that ICI
  collectives synchronize on (SURVEY §5.8 mapping).
- Frames are length-prefixed pickles; per-peer receiver threads drain
  sockets continuously so bulk sends can never deadlock the mesh.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import queue
import socket
import struct
import threading
import time as _walltime
import warnings
from typing import Any, Sequence

import numpy as np

from pathway_tpu.engine.batch import (
    Columns,
    DeltaBatch,
    apply_batch_to_state,
    columnarize_entries,
)
from pathway_tpu.engine.device import VECTOR_THRESHOLD
from pathway_tpu.engine.graph import (
    ErrorLogNode,
    InputSession,
    Node,
    Scope,
    StaticSource,
    SubscribeNode,
)
from pathway_tpu.engine.routing import (
    columnar_shards,
    entry_shards,
    shards_of_values,
)
from pathway_tpu.engine.sharded import (
    _VERIFY_ELISION,
    _assert_colocated,
    partition_rule,
    partitioner,
)
from pathway_tpu.engine.value import Pointer

_LEN = struct.Struct(">Q")
_MAC_LEN = hashlib.sha256().digest_size
#: refuse frames beyond this size BEFORE allocating — an unauthenticated
#: sender must not be able to drive unbounded buffering via the length
#: prefix (the MAC also covers the length, so a tampered prefix fails)
_MAX_FRAME = int(
    os.environ.get("PATHWAY_EXCHANGE_MAX_FRAME", str(1 << 31))
)


def _mesh_secret() -> bytes:
    """Shared frame-authentication key for the exchange mesh.

    Frames are pickles, so an unauthenticated peer that can reach an
    exchange port could otherwise execute arbitrary code. Every frame
    carries an HMAC-SHA256 over its payload; frames that fail
    verification tear the connection down before ``pickle.loads`` ever
    sees the bytes. ``pathway spawn`` generates a fresh secret per run
    (cli.py); multi-host deployments must set PATHWAY_EXCHANGE_SECRET to
    the same value on every host."""
    secret = os.environ.get("PATHWAY_EXCHANGE_SECRET") or os.environ.get(
        "PATHWAY_RUN_ID"
    )
    return secret.encode() if secret else b""

def _validated_float(name: str, default: float, minimum: float) -> float:
    """Parse a float env knob with a clear startup error for nonsense."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not a number (expected seconds, e.g. "
            f"{name}={default:g})"
        ) from None
    if not value >= minimum or value != value or value == float("inf"):
        raise ValueError(
            f"{name}={raw!r} out of range: must be a finite number "
            f">= {minimum:g} seconds"
        )
    return value


def _validated_int(name: str, default: int, minimum: int) -> int:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not an integer (e.g. {name}={default})"
        ) from None
    if value < minimum:
        raise ValueError(
            f"{name}={raw!r} out of range: must be >= {minimum}"
        )
    return value


#: how long a process waits for a peer frame before declaring the run
#: dead. ``PATHWAY_TPU_MESH_TIMEOUT`` is the canonical knob; the legacy
#: ``PATHWAY_EXCHANGE_TIMEOUT`` spelling is honoured as a fallback.
RECV_TIMEOUT = _validated_float(
    "PATHWAY_TPU_MESH_TIMEOUT",
    _validated_float("PATHWAY_EXCHANGE_TIMEOUT", 600.0, 0.001),
    0.001,
)
#: a peer silent this long while the mesh is otherwise alive is declared
#: hung (same recovery path as a dead socket); derived from the mesh
#: timeout unless pinned explicitly
SUSPICION_TIMEOUT = _validated_float(
    "PATHWAY_TPU_MESH_SUSPICION", RECV_TIMEOUT, 0.001
)
#: per-peer receive-queue high-water mark — a flooding or stalled peer
#: blocks (TCP backpressure) instead of growing leader memory unboundedly
QUEUE_HWM = _validated_int("PATHWAY_TPU_MESH_QUEUE_HWM", 512, 1)
_CONNECT_DEADLINE = 60.0


class MeshConfigWarning(UserWarning):
    """Structured warning for contradictory mesh knob combinations, in the
    analyzer's PW-code style (``PWF`` = pathway fault-tolerance)."""

    def __init__(self, code: str, message: str) -> None:
        super().__init__(f"{code}: {message}")
        self.code = code


def retry_backoff_ceiling_s(retries: int) -> float:
    """Worst-case wall time the bounded send-retry path can spend before
    giving up: per attempt, the jittered backoff sleep (delay starts at
    50ms, doubles, caps at 1s, jitter factor <= 1.5) plus the 2s
    ``_repair_link`` dial deadline."""
    total = 0.0
    delay = 0.05
    for _ in range(max(0, retries)):
        total += delay * 1.5 + 2.0
        delay = min(delay * 2, 1.0)
    return total


_KNOBS_VALIDATED = False


def validate_mesh_knobs(*, _force: bool = False) -> list[MeshConfigWarning]:
    """Cross-check independently tuned mesh knobs at startup (once per
    process; tests pass ``_force=True`` after monkeypatching the env).

    PWF001: the send-retry backoff ceiling must stay below the suspicion
    timeout — otherwise a sender can still be inside its retry loop when
    the peer declares *it* hung, turning one transient link glitch into a
    mutual-suspicion recovery storm.  Recomputed from the environment (not
    the module constants) so tests can exercise contradictory settings
    without reloading the module."""
    global _KNOBS_VALIDATED
    if _KNOBS_VALIDATED and not _force:
        return []
    _KNOBS_VALIDATED = True
    recv_timeout = _validated_float(
        "PATHWAY_TPU_MESH_TIMEOUT",
        _validated_float("PATHWAY_EXCHANGE_TIMEOUT", 600.0, 0.001),
        0.001,
    )
    suspicion = _validated_float(
        "PATHWAY_TPU_MESH_SUSPICION", recv_timeout, 0.001
    )
    retries = _validated_int("PATHWAY_TPU_MESH_SEND_RETRIES", 2, 0)
    found: list[MeshConfigWarning] = []
    ceiling = retry_backoff_ceiling_s(retries)
    if ceiling >= suspicion:
        found.append(
            MeshConfigWarning(
                "PWF001",
                f"mesh send-retry backoff ceiling ({ceiling:.2f}s for "
                f"PATHWAY_TPU_MESH_SEND_RETRIES={retries}) is not below "
                f"the suspicion timeout (PATHWAY_TPU_MESH_SUSPICION="
                f"{suspicion:g}s) — a retrying sender can be declared "
                f"hung mid-retry; raise the suspicion timeout or lower "
                f"the retry count",
            )
        )
    for w in found:
        warnings.warn(w, stacklevel=2)
    return found


def elect_leader(survivors: set[int] | list[int]) -> int:
    """Deterministic leader election: the lowest-rank live worker wins.
    Every survivor computes the same answer locally from the same
    membership view, so no voting round is needed — the epoch stamp on
    the election command is what serialises concurrent views."""
    if not survivors:
        raise ValueError("cannot elect a leader from an empty mesh")
    return min(survivors)


class EpochFence:
    """Per-command-kind epoch fencing.

    Recovery-control frames (``recover``, ``rollback``, ``elect``, …)
    carry the mesh epoch that issued them.  A frame whose epoch is not
    newer than the last one *processed* for that kind is stale — either a
    zombie ex-leader flushing its socket buffer after being fenced out,
    or a fault-injected duplicate of a command we already executed — and
    must be ignored rather than re-executed (re-running a rollback would
    deadlock the resync barrier).  Startup commands are stamped epoch 0
    and pass against the initial floor of -1."""

    def __init__(self) -> None:
        self._last: dict[str, int] = {}

    def admit(self, kind: str, epoch: int) -> bool:
        """True (and advances the fence) when the frame is fresh."""
        if epoch <= self._last.get(kind, -1):
            _metrics.REGISTRY.counter(
                "pathway_mesh_fenced_frames_total",
                "stale epoch-stamped control frames rejected by fencing",
            ).inc(1)
            _metrics.FLIGHT.record(
                "fenced_frame", frame_kind=kind, epoch=epoch,
                fence=self._last.get(kind, -1),
            )
            return False
        self._last[kind] = epoch
        return True

    def floor(self, kind: str) -> int:
        return self._last.get(kind, -1)


# -- snapshot-stream wire protocol (read tier) -----------------------------
#
# The serving read tier (pathway_tpu/serving/stream.py + replica.py)
# ships commit-stamped ReadSnapshot payloads from each worker to read-
# only replica processes over the SAME wire format as exchange frames:
# length prefix, HMAC-SHA256 over (length || payload), pickled body.
# Frame kinds (all fixed 4-tuples, epoch-stamped for fencing):
#
# - ``("snap-sub",      epoch, from_seq,  replica_id)`` replica -> worker
# - ``("snap-hello",    epoch, width,     process_id)`` worker  -> replica
# - ``("snap",          epoch, seq,       payload)``    worker  -> replica
# - ``("snap-rollback", epoch, to_time,   process_id)`` worker  -> replica
# - ``("snap-stats",    epoch, replica_id, snapshot)``  replica -> worker
#
# Replicas run an :class:`EpochFence` over the stream: ``snap`` frames
# from an epoch below the fence floor are a zombie publisher's and are
# dropped; ``snap-rollback`` is a control command admitted exactly once
# per epoch (re-running a truncate is harmless, but the fence keeps the
# duplicate/zombie semantics identical to the mesh control plane).

#: snapshot-stream frame kinds (subset of the mesh frame namespace)
SNAP_STREAM_KINDS = (
    "snap-sub",
    "snap-hello",
    "snap",
    "snap-rollback",
    "snap-stats",
)


def send_stream_frame(
    sock: socket.socket, frame: Any, secret: bytes | None = None
) -> None:
    """Authenticated frame write for the snapshot stream (same wire
    format as :meth:`MeshTransport._send`, usable without a mesh)."""
    if secret is None:
        secret = _mesh_secret()
    payload = pickle.dumps(frame, protocol=pickle.HIGHEST_PROTOCOL)
    len_bytes = _LEN.pack(len(payload))
    mac = hmac.new(secret, len_bytes + payload, hashlib.sha256).digest()
    sock.sendall(len_bytes + mac + payload)


def recv_stream_frame(
    sock: socket.socket, secret: bytes | None = None
) -> Any:
    """Authenticated frame read for the snapshot stream.  Verifies the
    HMAC BEFORE deserializing — a forged frame must never reach
    ``pickle.loads`` (same contract as :meth:`MeshTransport._read_frame`)."""
    if secret is None:
        secret = _mesh_secret()

    def read_exact(n: int) -> bytes:
        chunks = []
        while n:
            chunk = sock.recv(min(n, 1 << 20))
            if not chunk:
                raise ConnectionError("stream peer closed")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    len_bytes = read_exact(_LEN.size)
    (length,) = _LEN.unpack(len_bytes)
    if length > _MAX_FRAME:
        raise ConnectionError(
            f"snapshot-stream frame of {length} bytes exceeds "
            f"PATHWAY_EXCHANGE_MAX_FRAME={_MAX_FRAME}"
        )
    mac = read_exact(_MAC_LEN)
    payload = read_exact(length)
    expected = hmac.new(
        secret, len_bytes + payload, hashlib.sha256
    ).digest()
    if not hmac.compare_digest(mac, expected):
        raise ConnectionError(
            "snapshot-stream frame failed HMAC authentication "
            "(PATHWAY_EXCHANGE_SECRET mismatch or foreign traffic)"
        )
    return pickle.loads(payload)


class PeerLostError(RuntimeError):
    """A peer's socket died, its frames timed out, or it announced an
    abort mid-round.  Recoverable when a MeshSupervisor + operator
    snapshots are configured; fail-stop otherwise."""

    def __init__(self, message: str, peer: int | None = None) -> None:
        super().__init__(message)
        self.peer = peer


# ---------------------------------------------------------------------------
# Columnar wire frames
# ---------------------------------------------------------------------------

#: kill-switch (and the bench's row-pickle baseline): "0" forces every
#: exchange back onto pickled row entries
COLUMNAR_EXCHANGE = os.environ.get(
    "PATHWAY_EXCHANGE_COLUMNAR", "1"
).lower() not in ("0", "false")

#: probe counters for tests/benchmarks: columnar frames this process
#: encoded for / decoded from remote peers, row-entry deliveries that took
#: the pickle fallback, and optimizer-elided exchanges.  The dict now
#: lives in engine/routing.py (shared with the in-process scheduler); the
#: import below keeps every historical access path
#: (``distributed.EXCHANGE_STATS``) pointing at the same object.
from pathway_tpu.engine.routing import EXCHANGE_STATS  # noqa: E402
from pathway_tpu.internals import metrics as _metrics  # noqa: E402
from pathway_tpu.internals import profiling as _profiling  # noqa: E402
from pathway_tpu.internals import timeseries as _timeseries  # noqa: E402
from pathway_tpu.internals import tracing as _tracing  # noqa: E402

_FRAME_MAGIC = b"PWCF"
_FRAME_VERSION = 1
_U8 = struct.Struct("<B")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def _frame_encodable(columns: Columns) -> bool:
    """True when every data column is a fixed-width clean dtype whose raw
    C-order buffer round-trips (bool/int/uint/float/unicode/datetime).
    Object columns (mixed types, tuples, Json) take the pickled-entry
    fallback instead."""
    return all(c.dtype.kind not in "OV" for c in columns.cols)


def encode_columns_frame(columns: Columns) -> bytes | None:
    """Dtype-tagged columnar frame — the wire form of a ``Columns``
    payload; no row is ever materialised or pickled.

    Layout (integers little-endian; every variable block length-prefixed):

        magic b"PWCF" | version u8 | flags u8 | n_rows u32 | n_cols u32
        key block: n_rows x 16 raw little-endian key bytes
        diff block (flags & 1): n_rows x int64
        per column: u8 tag length + ascii numpy ``dtype.str`` tag,
                    u64 buffer length + raw C-order column buffer

    Returns ``None`` when the payload cannot be represented (object-dtype
    column, key derivation failure) — callers fall back to row entries.
    The transport length-prefixes and HMACs the enclosing mesh frame, so
    this buffer needs no own authentication.
    """
    if not _frame_encodable(columns):
        return None
    trace = _tracing.current()
    if trace is not None:
        t0 = _walltime.perf_counter()
        frame = _encode_columns_frame(columns)
        trace.span(
            "pwcf-encode",
            "exchange",
            t0,
            _walltime.perf_counter(),
            rows=columns.n,
            cols=len(columns.cols),
            bytes=0 if frame is None else len(frame),
        )
        return frame
    return _encode_columns_frame(columns)


def _encode_columns_frame(columns: Columns) -> bytes | None:
    try:
        kb = np.ascontiguousarray(columns.kbytes(), np.uint8)
    except Exception:  # lazy key thunk failed: row path derives the keys
        return None
    diffs = columns.diffs
    parts = [
        _FRAME_MAGIC,
        _U8.pack(_FRAME_VERSION),
        _U8.pack(1 if diffs is not None else 0),
        _U32.pack(columns.n),
        _U32.pack(len(columns.cols)),
        kb.tobytes(),
    ]
    if diffs is not None:
        parts.append(np.ascontiguousarray(diffs, np.int64).tobytes())
    for col in columns.cols:
        tag = col.dtype.str.encode("ascii")
        buf = np.ascontiguousarray(col).tobytes()
        parts.append(_U8.pack(len(tag)))
        parts.append(tag)
        parts.append(_U64.pack(len(buf)))
        parts.append(buf)
    return b"".join(parts)


def decode_columns_frame(frame: bytes) -> Columns:
    """Inverse of :func:`encode_columns_frame`; arrays are zero-copy views
    into the frame buffer (batch payloads are immutable downstream)."""
    if frame[:4] != _FRAME_MAGIC:
        raise ValueError("bad columnar frame magic")
    version = frame[4]
    if version != _FRAME_VERSION:
        raise ValueError(f"unsupported columnar frame version {version}")
    flags = frame[5]
    (n,) = _U32.unpack_from(frame, 6)
    (ncols,) = _U32.unpack_from(frame, 10)
    pos = 14
    kb = np.frombuffer(frame, np.uint8, n * 16, pos).reshape(n, 16)
    pos += n * 16
    diffs = None
    if flags & 1:
        diffs = np.frombuffer(frame, np.int64, n, pos)
        pos += n * 8
    cols = []
    for _ in range(ncols):
        tlen = frame[pos]
        pos += 1
        dt = np.dtype(frame[pos : pos + tlen].decode("ascii"))
        pos += tlen
        (blen,) = _U64.unpack_from(frame, pos)
        pos += 8
        cols.append(np.frombuffer(frame, dt, n, pos))
        pos += blen
    return Columns(n, cols, kbytes=kb, diffs=diffs)


def default_addresses(n_processes: int, first_port: int) -> list[tuple[str, int]]:
    """Static address book (reference config.rs:113-117: 127.0.0.1,
    first_port+i). Multi-host deployments override via
    PATHWAY_PROCESS_ADDRESSES="host1:port1;host2:port2;..."."""
    spec = os.environ.get("PATHWAY_PROCESS_ADDRESSES")
    if spec:
        out = []
        for part in spec.split(";"):
            host, _, port = part.strip().rpartition(":")
            out.append((host, int(port)))
        if len(out) != n_processes:
            raise ValueError(
                f"PATHWAY_PROCESS_ADDRESSES lists {len(out)} hosts for "
                f"{n_processes} processes"
            )
        return out
    return [("127.0.0.1", first_port + i) for i in range(n_processes)]


class MeshTransport:
    """Full TCP mesh; one duplex socket per process pair.

    Process ``i`` accepts connections from peers ``j > i`` and dials peers
    ``j < i``; a HELLO frame identifies the dialer. One receiver thread per
    peer parses frames into a FIFO queue (per-peer streams are totally
    ordered, and the round protocol is globally sequenced per peer, so a
    plain queue is a sufficient demultiplexer)."""

    def __init__(
        self,
        process_id: int,
        n_processes: int,
        first_port: int = 10000,
        addresses: Sequence[tuple[str, int]] | None = None,
    ) -> None:
        self.process_id = process_id
        self.n = n_processes
        addrs = list(addresses or default_addresses(n_processes, first_port))
        self._addrs = addrs
        self._socks: dict[int, socket.socket] = {}
        # bounded per-peer queues: a flooding or stalled peer exerts TCP
        # backpressure at the high-water mark instead of growing this
        # process's memory without limit (frames are NEVER dropped — the
        # round protocol cannot survive a missing frame)
        self._queues: dict[int, queue.Queue] = {
            p: queue.Queue(maxsize=QUEUE_HWM)
            for p in range(n_processes)
            if p != process_id
        }
        self._send_locks: dict[int, threading.Lock] = {}
        self._threads: list[threading.Thread] = []
        self._closed = False
        #: serializes the liveness state below — written by every recv
        #: loop and by the pump thread's suspicion scan
        self._peer_lock = threading.Lock()
        #: peers whose socket closed/errored (set by the recv loops)
        self.dead_peers: set[int] = set()  # guarded-by: self._peer_lock
        #: per-peer monotonic arrival time of the most recent frame
        #: (heartbeats included) — the liveness signal suspicion reads
        # guarded-by: self._peer_lock
        self.last_seen: dict[int, float] = {
            p: _walltime.monotonic()
            for p in range(n_processes)
            if p != process_id
        }
        self._secret = _mesh_secret()
        self._backpressure = _metrics.REGISTRY.gauge(
            "pathway_mesh_recv_backpressure",
            "receiver threads currently blocked on a full peer queue",
        )
        self._fault_plan = None
        if os.environ.get("PATHWAY_TPU_FAULT_PLAN"):
            from pathway_tpu.engine.faults import active_plan

            self._fault_plan = active_plan()
        validate_mesh_knobs()
        if n_processes == 1:
            return
        # bind only the configured interface (127.0.0.1 by default) — not
        # 0.0.0.0 — so single-host meshes are unreachable off-box. NAT'd
        # deployments whose advertised address is not locally bindable
        # (Docker bridge) set PATHWAY_EXCHANGE_BIND (e.g. to 0.0.0.0).
        bind_host = os.environ.get(
            "PATHWAY_EXCHANGE_BIND", addrs[process_id][0]
        )
        self._bind_host = bind_host
        loopback = ("127.0.0.1", "localhost", "::1")
        exposed = bind_host not in loopback or any(
            host not in loopback for host, _port in addrs
        )
        if exposed and not os.environ.get("PATHWAY_EXCHANGE_SECRET"):
            # an off-loopback listener with a missing/guessable key would
            # hand pickle.loads to anyone who can reach the port
            raise RuntimeError(
                "a non-loopback exchange listener requires "
                "PATHWAY_EXCHANGE_SECRET (the same value on every host) "
                "to authenticate peer frames"
            )
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((bind_host, addrs[process_id][1]))
        listener.listen(n_processes)
        listener.settimeout(_CONNECT_DEADLINE)
        try:
            for peer in range(process_id):  # dial lower ids
                self._socks[peer] = self._dial(addrs[peer])
                self._send(peer, ("hello", process_id))
            for _ in range(process_id + 1, n_processes):  # accept higher ids
                conn, _addr = listener.accept()
                conn.settimeout(None)
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                frame = self._read_frame(conn)
                if (
                    not isinstance(frame, tuple)
                    or len(frame) != 2
                    or frame[0] != "hello"
                    or not isinstance(frame[1], int)
                    or not 0 <= frame[1] < n_processes
                ):
                    raise RuntimeError(
                        f"process {process_id}: bad handshake on exchange "
                        f"port: {frame!r}"
                    )
                self._socks[frame[1]] = conn
        finally:
            listener.close()
        for peer, sock in self._socks.items():
            self._start_recv(peer, sock)

    def _start_recv(self, peer: int, sock: socket.socket) -> None:
        self._send_locks[peer] = threading.Lock()
        t = threading.Thread(
            target=self._recv_loop, args=(peer, sock), daemon=True
        )
        t.start()
        self._threads.append(t)

    @staticmethod
    def _dial(addr: tuple[str, int]) -> socket.socket:
        deadline = _walltime.monotonic() + _CONNECT_DEADLINE
        delay = 0.02
        while True:
            try:
                sock = socket.create_connection(addr, timeout=_CONNECT_DEADLINE)
                # the connect timeout must not linger: receiver threads
                # block in recv indefinitely between commits (quiet
                # follower-follower links would otherwise fake-EOF at 60s)
                sock.settimeout(None)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return sock
            except OSError:
                if _walltime.monotonic() > deadline:
                    raise
                _walltime.sleep(delay)
                delay = min(delay * 2, 0.5)

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> bytes:
        chunks = []
        while n:
            chunk = sock.recv(min(n, 1 << 20))
            if not chunk:
                raise ConnectionError("peer closed")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def _read_frame(self, sock: socket.socket) -> Any:
        len_bytes = self._read_exact(sock, _LEN.size)
        (length,) = _LEN.unpack(len_bytes)
        if length > _MAX_FRAME:
            raise ConnectionError(
                f"exchange frame of {length} bytes exceeds "
                f"PATHWAY_EXCHANGE_MAX_FRAME={_MAX_FRAME}"
            )
        mac = self._read_exact(sock, _MAC_LEN)
        payload = self._read_exact(sock, length)
        # authenticate BEFORE deserializing: a forged frame must never
        # reach pickle.loads (ADVICE r2: unauthenticated pickle = RCE)
        expected = hmac.new(
            self._secret, len_bytes + payload, hashlib.sha256
        ).digest()
        if not hmac.compare_digest(mac, expected):
            raise ConnectionError(
                "exchange frame failed HMAC authentication "
                "(PATHWAY_EXCHANGE_SECRET mismatch or foreign traffic)"
            )
        return pickle.loads(payload)

    def _recv_loop(self, peer: int, sock: socket.socket) -> None:
        q = self._queues[peer]
        try:
            while True:
                frame = self._read_frame(sock)
                with self._peer_lock:
                    self.last_seen[peer] = _walltime.monotonic()
                if (
                    isinstance(frame, tuple)
                    and frame
                    and frame[0] == "hb"
                ):
                    # transport-level heartbeat: liveness recorded above,
                    # never surfaced to the round protocol
                    continue
                self._put(q, frame)
        except (ConnectionError, OSError, EOFError, pickle.PickleError):
            # mark BEFORE enqueueing: a coordinator that never recv()s
            # from this peer still observes the death via
            # raise_if_peer_dead() at its next pump tick — send-side
            # detection alone needs TWO sends after the RST (the first
            # one buffers), which stalls fail-stop for idle streams.
            # A loop whose socket was replaced by reestablish() must not
            # poison the fresh link.
            if self._socks.get(peer) is sock and not self._closed:
                with self._peer_lock:
                    self.dead_peers.add(peer)
                self._put(q, ("__eof__", peer))

    def _put(self, q: queue.Queue, frame: Any) -> None:
        """Blocking put with a backpressure gauge: at the high-water mark
        the receiver thread stalls, which stops reading the socket, which
        pushes back on the sender via TCP flow control."""
        try:
            q.put_nowait(frame)
            return
        except queue.Full:
            pass
        self._backpressure.value += 1
        try:
            q.put(frame)
        finally:
            self._backpressure.value -= 1

    def raise_if_peer_dead(self) -> None:
        """Fail-stop promptly when any peer's socket closed (reference
        teardown on worker loss, dataflow.rs:5854-5883).  A peer silent
        past the suspicion timeout (hung, not dead) raises the same way —
        its socket is torn down first so the two paths converge."""
        if self._closed:
            return
        if not self.dead_peers:
            now = _walltime.monotonic()
            with self._peer_lock:
                seen_snapshot = dict(self.last_seen)
            for peer, seen in seen_snapshot.items():
                if peer in self._socks and now - seen > SUSPICION_TIMEOUT:
                    # a hung peer holds its socket open: close it so the
                    # recv loop marks it dead like any other lost peer
                    try:
                        self._socks[peer].close()
                    except OSError:
                        pass
                    with self._peer_lock:
                        self.dead_peers.add(peer)
                    raise PeerLostError(
                        f"process {self.process_id}: peer {peer} silent "
                        f"for {now - seen:.1f}s (suspicion timeout "
                        f"{SUSPICION_TIMEOUT:g}s) — suspected hung",
                        peer=peer,
                    )
        if self.dead_peers:
            dead = sorted(self.dead_peers)
            raise PeerLostError(
                f"process {self.process_id}: peer(s) {dead} disconnected",
                peer=dead[0],
            )

    def _send(self, peer: int, frame: Any) -> None:
        payload = pickle.dumps(frame, protocol=pickle.HIGHEST_PROTOCOL)
        lock = self._send_locks.get(peer)
        len_bytes = _LEN.pack(len(payload))
        mac = hmac.new(
            self._secret, len_bytes + payload, hashlib.sha256
        ).digest()
        data = len_bytes + mac + payload
        if lock is None:
            self._socks[peer].sendall(data)
        else:
            with lock:
                # pwc-ok: PWC403 — per-peer lock serializes socket writers
                self._socks[peer].sendall(data)

    def send(self, peer: int, frame: Any) -> None:
        plan = self._fault_plan
        if plan is not None:
            action = plan.on_send(self.process_id, peer, frame)
            if action == "drop":
                return
            if action == "reset":
                # synthetic RST: hard-close the socket mid-stream, then
                # fall through so the send fails like a real reset would
                try:
                    self._socks[peer].close()
                except OSError:
                    pass
            elif action == "dup":
                try:
                    self._send(peer, frame)
                except OSError:
                    pass
        try:
            self._send(peer, frame)
        except OSError as exc:
            if self._retry_send(peer, frame):
                return
            raise PeerLostError(
                f"process {self.process_id}: lost connection to peer "
                f"{peer}",
                peer=peer,
            ) from exc

    def _retry_send(self, peer: int, frame: Any) -> bool:
        """Bounded retry for transient send failures: redial the link with
        exponential backoff + jitter (``PATHWAY_TPU_MESH_SEND_RETRIES``,
        default 2; 0 disables).  A peer the recv loop already declared
        dead is NOT retried — in-flight frames were lost, so transparent
        resending would corrupt the round protocol; the rollback-based
        recovery path owns that case."""
        retries = _validated_int("PATHWAY_TPU_MESH_SEND_RETRIES", 2, 0)
        if retries == 0 or self._closed or peer in self.dead_peers:
            return False
        import random as _random

        delay = 0.05
        for _attempt in range(retries):
            _walltime.sleep(delay * (0.5 + _random.random()))
            delay = min(delay * 2, 1.0)
            try:
                self._repair_link(peer, deadline=2.0)
                self._send(peer, frame)
            except (OSError, RuntimeError):
                continue
            _metrics.REGISTRY.counter(
                "pathway_mesh_send_retries_total",
                "mesh sends recovered by the bounded retry path",
            ).inc(1)
            return True
        return False

    def _repair_link(self, peer: int, deadline: float) -> None:
        """Re-create the duplex socket to ``peer`` (dial-lower/accept-
        higher, same as startup) and restart its receiver thread."""
        old = self._socks.get(peer)
        if peer < self.process_id:
            sock = socket.create_connection(
                self._addrs[peer], timeout=deadline
            )
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks[peer] = sock
            self._send(peer, ("hello", self.process_id))
        else:
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind(
                (self._bind_host, self._addrs[self.process_id][1])
            )
            listener.listen(self.n)
            listener.settimeout(deadline)
            try:
                conn, _addr = listener.accept()
                conn.settimeout(None)
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                frame = self._read_frame(conn)
                if (
                    not isinstance(frame, tuple)
                    or len(frame) != 2
                    or frame[0] != "hello"
                    or frame[1] != peer
                ):
                    conn.close()
                    raise RuntimeError(
                        f"process {self.process_id}: expected hello from "
                        f"peer {peer} on repair, got {frame!r}"
                    )
                self._socks[peer] = conn
            finally:
                listener.close()
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        self._start_recv(peer, self._socks[peer])

    def reestablish(self, peer: int, deadline: float = 30.0) -> None:
        """Reconnect to a restarted ``peer``: fresh socket, fresh (empty)
        frame queue, fresh receiver thread, liveness state reset.  The
        restarted process runs its normal constructor (bind, dial lower
        ids, accept higher ids), so survivors mirror that from the other
        side: lower ids accept the dial-in, higher ids dial its listener."""
        self._queues[peer] = queue.Queue(maxsize=QUEUE_HWM)
        end = _walltime.monotonic() + deadline
        delay = 0.05
        while True:
            try:
                self._repair_link(
                    peer, deadline=max(0.1, end - _walltime.monotonic())
                )
                break
            except (OSError, RuntimeError):
                if _walltime.monotonic() > end:
                    raise PeerLostError(
                        f"process {self.process_id}: could not "
                        f"re-establish the link to restarted peer {peer} "
                        f"within {deadline:g}s",
                        peer=peer,
                    )
                _walltime.sleep(delay)
                delay = min(delay * 2, 0.5)
        with self._peer_lock:
            self.dead_peers.discard(peer)
            self.last_seen[peer] = _walltime.monotonic()

    def heartbeat(self, peer: int) -> None:
        """Best-effort idle-time liveness frame; absorbed by the peer's
        receiver thread (never enters its protocol queue)."""
        try:
            self._send(peer, ("hb", self.process_id, _walltime.time()))
        except OSError:
            pass  # the recv loop / send path owns failure detection

    def broadcast(self, frame: Any) -> None:
        for peer in self._queues:
            self.send(peer, frame)

    def recv(self, peer: int, timeout: float = RECV_TIMEOUT) -> Any:
        try:
            frame = self._queues[peer].get(timeout=timeout)
        except queue.Empty:
            raise PeerLostError(
                f"process {self.process_id}: no frame from peer {peer} "
                f"within {timeout}s — a peer likely crashed",
                peer=peer,
            ) from None
        if isinstance(frame, tuple) and frame and frame[0] == "__eof__":
            raise PeerLostError(
                f"process {self.process_id}: peer {peer} disconnected",
                peer=peer,
            )
        return frame

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for sock in self._socks.values():
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class DistributedScheduler:
    """The per-process commit pump of the multi-process runtime.

    Mirrors engine/sharded.py ShardedScheduler over ``threads`` local scope
    replicas, with remote workers reached through the mesh. Process 0's
    scope 0 is the primary replica: sources flush there, sinks and
    globally-stateful operators are pinned there."""

    def __init__(
        self,
        local_scopes: Sequence[Scope],
        process_id: int,
        n_processes: int,
        transport: MeshTransport,
        n_shared: int | None = None,
        probe: bool = False,
    ) -> None:
        self.scopes = list(local_scopes)
        for scope in self.scopes:
            # replica `current` holds key shards (see ShardedScheduler)
            scope.sharded = True
        self.threads = len(self.scopes)
        self.process_id = process_id
        self.n_processes = n_processes
        self.n_workers = self.threads * n_processes
        self.transport = transport
        self.time = 0
        self.probe = probe
        #: node index -> OperatorStats aggregated across LOCAL replicas
        #: (populated by _drain_local under probe; same read surface as
        #: Scheduler/ShardedScheduler for the monitor + mesh snapshots)
        self.stats: dict[int, Any] = {}
        #: peer process id -> last piggybacked metrics snapshot (leader
        #: only; followers attach theirs to round frames bound for 0)
        self.mesh_metrics: dict[int, dict] = {}
        #: peer process id -> spans piggybacked for the in-flight sampled
        #: trace (leader only; the runner assembles + clears per commit)
        self.trace_peer_spans: dict[int, list] = {}
        if probe:
            self._queue_gauge = _metrics.REGISTRY.gauge(
                "pathway_queue_depth",
                "operators with pending delta batches (backpressure)",
            )
        #: shared graph length: nodes with index >= n_shared exist only on
        #: process 0 / scope 0 (sink-side chains attached there). The
        #: runner measures it before attaching sink drivers; guessing it
        #: here (e.g. min over local scopes) silently desynchronizes
        #: routing when every local scope carries sink-side nodes
        #: (ADVICE r2), so it is required.
        if n_shared is None:
            raise ValueError(
                "n_shared is required: pass the shared graph length "
                "measured before sink drivers are attached "
                "(DistributedGraphRunner.attach_sinks records it)"
            )
        self.n_shared = n_shared
        #: producer index -> [(consumer index, port)] for process-0-only
        #: consumers, learned from the coordinator's topology broadcast
        self.extra_consumers: dict[int, list[tuple[int, int]]] = {}
        # local replicas must carry the identical shared operator sequence
        # (ShardedScheduler's divergence check, applied per process)
        sig0 = self._shared_signature()
        for idx, scope in enumerate(self.scopes[1:], start=1):
            sig = [type(n).__name__ for n in scope.nodes[: self.n_shared]]
            if sig != sig0:
                raise ValueError(
                    f"local worker {idx} scope diverged: the graph logic "
                    "must build the identical operator sequence on every "
                    "worker"
                )
        self._parts: dict[tuple[int, int], Any] = {}
        #: optimizer-proven redundant exchange edges; populated lazily by
        #: _ensure_optimized AFTER the topology handshake, so the type-name
        #: signatures above compare pre-rewrite graphs on every process
        self._elided: set = set()
        self._optimized = False
        #: deliveries queued for each remote process this round
        self._outbox: dict[int, list[tuple]] = {
            p: [] for p in range(n_processes) if p != process_id
        }
        #: peer process id -> wall-clock heartbeat stamp piggybacked on
        #: its most recent round frame (liveness evidence for post-mortems;
        #: the transport's monotonic ``last_seen`` drives suspicion)
        self.peer_heartbeats: dict[int, float] = {}
        #: a leader recover command that arrived MID-ROUND on a follower
        #: (stashed by _recv_round for the runner's park loop to consume)
        self._pending_recover: tuple | None = None
        #: per-kind epoch fence: rejects control frames from fenced-out
        #: zombie leaders and fault-injected duplicates (see EpochFence)
        self.fence = EpochFence()

    # -- topology ----------------------------------------------------------

    def _shared_signature(self) -> list[str]:
        return [
            type(n).__name__ for n in self.scopes[0].nodes[: self.n_shared]
        ]

    def announce_topology(self) -> None:
        """Process 0: tell peers about sink-side consumers so their
        producer replicas route output here (the sharded scheduler reads
        worker 0's superset scope directly; remote processes can't)."""
        assert self.process_id == 0
        scope0 = self.scopes[0]
        extra: list[tuple[int, int, int]] = []
        for node in scope0.nodes[: self.n_shared]:
            for consumer, port in node.consumers:
                if consumer.index >= self.n_shared:
                    extra.append((node.index, consumer.index, port))
        # rebuilt from scratch: announce may run again after a leader
        # restart, and appending twice would double-deliver to sinks
        self.extra_consumers = {}
        for prod, cons, port in extra:
            self.extra_consumers.setdefault(prod, []).append((cons, port))
        # kept verbatim for recovery: a restarted follower re-runs the
        # topology handshake against the SAME frame the originals saw
        self._topology_frame = (
            "topology", self.n_shared, self._shared_signature(), extra
        )
        self.transport.broadcast(self._topology_frame)
        self._ensure_optimized()

    def reannounce_to(self, peer: int) -> None:
        """Re-send the stored topology frame to one restarted peer (its
        fresh ``receive_topology`` runs the same divergence +
        ``_ensure_optimized`` fingerprint checks the original did)."""
        assert self.process_id == 0
        self.transport.send(peer, self._topology_frame)

    def receive_topology(self) -> None:
        frame = self.transport.recv(0)
        if not isinstance(frame, tuple) or len(frame) != 4 or frame[0] != "topology":
            raise RuntimeError(
                f"process {self.process_id}: expected the coordinator's "
                f"topology frame, got {frame!r}"
            )
        _kind, n_shared, signature, extra = frame
        if n_shared != self.n_shared or signature != self._shared_signature():
            raise RuntimeError(
                "graph divergence: the program must build the identical "
                f"operator graph in every process (coordinator has "
                f"{n_shared} shared nodes {signature[:6]}..., process "
                f"{self.process_id} has {self.n_shared} "
                f"{self._shared_signature()[:6]}...)"
            )
        # rebuilt, not appended: survivors re-run this handshake against a
        # restarted or newly elected leader, and duplicate consumer edges
        # would double-deliver every sink row
        self.extra_consumers = {}
        for prod, cons, port in extra:
            self.extra_consumers.setdefault(prod, []).append((cons, port))
        self._ensure_optimized()

    def _ensure_optimized(self) -> None:
        """Run the pre-execution rewriter once, after the topology
        handshake: the decision inputs (shared region + producers with
        off-process sink consumers) are then identical on every process,
        so every replica graph mutates the same way."""
        if self._optimized:
            return
        self._optimized = True
        from pathway_tpu.optimize import optimize_scopes

        self._elided = optimize_scopes(
            self.scopes,
            n_shared=self.n_shared,
            protected=set(self.extra_consumers),
        )

    # -- worker placement --------------------------------------------------

    def _owner(self, worker: int) -> tuple[int, int]:
        """worker -> (process, local scope idx)."""
        return worker // self.threads, worker % self.threads

    def _partition_fn(self, consumer: Node, port: int):
        key = (consumer.index, port)
        fn = self._parts.get(key, False)
        if fn is False:
            fn = partitioner(consumer, port, self.n_workers)
            self._parts[key] = fn
        return fn

    def _push_remote(
        self,
        process: int,
        kind: str,
        index: int,
        port_or_worker: int,
        worker: int,
        entries: list,
        consolidated: bool,
        insert_only: bool = False,
    ) -> None:
        if kind == "push":
            EXCHANGE_STATS["row_batches_sent"] += 1
        self._outbox[process].append(
            (kind, index, port_or_worker, worker, entries, consolidated,
             insert_only)
        )

    def _push_remote_columnar(
        self,
        process: int,
        kind: str,
        index: int,
        port_or_worker: int,
        worker: int,
        frame: bytes,
        consolidated: bool,
        insert_only: bool,
        raw_insert_only: bool,
    ) -> None:
        EXCHANGE_STATS["columnar_frames_sent"] += 1
        self._outbox[process].append(
            (kind, index, port_or_worker, worker, frame, consolidated,
             insert_only, raw_insert_only)
        )

    def _push_remote_batch(
        self,
        process: int,
        cons_idx: int,
        port: int,
        worker: int,
        out: DeltaBatch,
    ) -> None:
        """Ship a WHOLE batch to one remote worker: a columnar frame when
        the payload allows it, pickled row entries otherwise."""
        if (
            COLUMNAR_EXCHANGE
            and out._entries is None
            and out.columns is not None
        ):
            frame = encode_columns_frame(out.columns)
            if frame is not None:
                self._push_remote_columnar(
                    process, "cpush", cons_idx, port, worker, frame,
                    out._consolidated, out._insert_only,
                    out._raw_insert_only,
                )
                return
        self._push_remote(
            process, "push", cons_idx, port, worker, out.entries,
            out._consolidated, out._insert_only,
        )

    def _local_push(
        self, scope_idx: int, consumer_index: int, port: int, entries: list,
        consolidated: bool, insert_only: bool = False,
    ) -> None:
        batch = DeltaBatch(entries)
        batch._consolidated = consolidated
        batch._insert_only = insert_only
        self.scopes[scope_idx].nodes[consumer_index].push(port, batch)

    # -- exchange ----------------------------------------------------------

    def _deliver(
        self, producer: Node, out: DeltaBatch, scope_idx: int = 0
    ) -> None:
        """Split ``out`` per consumer; push each part to the consumer's
        replica on the owning worker (local) or queue it for the owning
        process (remote).  ``scope_idx`` is the local replica that produced
        ``out`` — elided edges stay on that worker."""
        elided = self._elided
        for consumer, port in self.scopes[0].nodes[producer.index].consumers:
            if (producer.index, consumer.index, port) in elided:
                # optimizer-proven redundant exchange: skip the routing
                # digests AND the PWCF encode/decode round-trip — the
                # whole batch already lives on this worker's replica
                if _VERIFY_ELISION:
                    _assert_colocated(
                        consumer, port, out,
                        self.process_id * self.threads + scope_idx,
                        self.n_workers,
                    )
                EXCHANGE_STATS["elided"] += 1
                EXCHANGE_STATS["repartitions"] += 1
                self.scopes[scope_idx].nodes[consumer.index].push(port, out)
                continue
            self._route_part(consumer.index, port, consumer, out)
        # sink-side consumers exist only on process 0 / scope 0. Process 0
        # reads them from its own superset consumer lists above (for every
        # local replica); remote processes route from the broadcast topology.
        if self.process_id != 0:
            for cons_idx, port in self.extra_consumers.get(producer.index, ()):
                EXCHANGE_STATS["host_deliveries"] += 1
                EXCHANGE_STATS["repartitions"] += 1
                self._push_remote_batch(0, cons_idx, port, 0, out)

    def _route_part(
        self,
        cons_idx: int,
        port: int,
        consumer: Node,
        out: DeltaBatch,
    ) -> None:
        if cons_idx >= self.n_shared or self._partition_fn(consumer, port) is None:
            # pinned whole to worker 0 (sink chain / globally-stateful op):
            # push the batch object itself, no copy (ShardedScheduler does
            # the same — consumers never mutate received batches)
            EXCHANGE_STATS["host_deliveries"] += 1
            EXCHANGE_STATS["repartitions"] += 1
            if self.process_id == 0:
                self.scopes[0].nodes[cons_idx].push(port, out)
            else:
                self._push_remote_batch(0, cons_idx, port, 0, out)
            return
        if (
            COLUMNAR_EXCHANGE
            and out._entries is None
            and out.columns is not None
        ):
            shards = columnar_shards(
                partition_rule(consumer, port), out.columns, self.n_workers
            )
            if shards is not None and self._route_columnar(
                cons_idx, port, out, shards, consumer=consumer
            ):
                return
        EXCHANGE_STATS["host_deliveries"] += 1
        EXCHANGE_STATS["repartitions"] += 1
        parts: list[list] = [[] for _ in range(self.n_workers)]
        shards = entry_shards(
            partition_rule(consumer, port), out.entries, self.n_workers
        )
        if shards is not None:
            # batched worker assignment (one digest kernel call), same
            # per-row definition as the partitioner closures
            for e, w in zip(out.entries, shards):
                parts[w].append(e)
        else:
            fn = self._partition_fn(consumer, port)
            for key, row, diff in out:
                parts[fn(key, row)].append((key, row, diff))
        for worker, entries in enumerate(parts):
            if not entries:
                continue
            process, scope_idx = self._owner(worker)
            if process == self.process_id:
                self._local_push(
                    scope_idx, cons_idx, port, entries,
                    out._consolidated, out._insert_only,
                )
            else:
                self._push_remote(
                    process, "push", cons_idx, port, worker, entries,
                    out._consolidated, out._insert_only,
                )

    def _route_columnar(
        self,
        cons_idx: int,
        port: int,
        out: DeltaBatch,
        shards: np.ndarray,
        consumer: "Node | None" = None,
    ) -> bool:
        """Route a columnar batch by a precomputed shard vector: local
        shards push gathered ``Columns`` (no serialization at all), remote
        shards ship dtype-tagged frames. Returns False — with NO pushes
        performed — when some shard must go remote but the payload cannot
        frame-encode, so the caller's row path handles the whole batch.

        When every destination worker is local to THIS process (the
        single-process mesh — worker threads sharing one device pool),
        the repartition may go through the device collective instead of
        the per-worker gather loop; declines fall through to the host
        split below.  Cross-process destinations keep the TCP/PWCF plane:
        device collectives only span one process's JAX mesh."""
        from pathway_tpu.engine import collective_exchange as _collective

        cols = out.columns
        workers = np.unique(shards).tolist()
        any_remote = any(
            self._owner(w)[0] != self.process_id for w in workers
        )
        if not any_remote:
            cparts = _collective.exchange(
                cons_idx,
                cols,
                shards,
                self.n_workers,
                consumer=consumer,
            )
            if cparts is not None:
                EXCHANGE_STATS["collective_deliveries"] += 1
                EXCHANGE_STATS["repartitions"] += 1
                for worker, part in enumerate(cparts):
                    if part is None:
                        continue
                    _process, scope_idx = self._owner(worker)
                    batch = DeltaBatch.from_columns(
                        part,
                        consolidated=out._consolidated,
                        insert_only=out._insert_only,
                    )
                    batch._raw_insert_only = out._raw_insert_only
                    self.scopes[scope_idx].nodes[cons_idx].push(port, batch)
                return True
        if any_remote:
            if not _frame_encodable(cols):
                return False
            try:
                cols.kbytes()  # force lazy keys BEFORE any local push
            except Exception:
                return False
        EXCHANGE_STATS["host_deliveries"] += 1
        EXCHANGE_STATS["repartitions"] += 1
        track = not any_remote and _collective.tracking(self.n_workers)
        t0 = _walltime.perf_counter_ns() if track else 0
        for worker in workers:
            idx = np.flatnonzero(shards == worker)
            part = cols.gather(idx)
            process, scope_idx = self._owner(worker)
            if process == self.process_id:
                batch = DeltaBatch.from_columns(
                    part,
                    consolidated=out._consolidated,
                    insert_only=out._insert_only,
                )
                batch._raw_insert_only = out._raw_insert_only
                self.scopes[scope_idx].nodes[cons_idx].push(port, batch)
            else:
                frame = encode_columns_frame(part)
                assert frame is not None  # encodability proven above
                self._push_remote_columnar(
                    process, "cpush", cons_idx, port, worker, frame,
                    out._consolidated, out._insert_only,
                    out._raw_insert_only,
                )
        if track:
            _collective.record_host(
                cons_idx, cols.n, _walltime.perf_counter_ns() - t0
            )
        return True

    def _apply_remote(self, deliveries: list[tuple]) -> bool:
        got = False
        for delivery in deliveries:
            got = True
            kind = delivery[0]
            if kind in ("cpush", "cstate"):
                (
                    _kind, index, port_or_worker, worker, frame,
                    consolidated, insert_only, raw_insert_only,
                ) = delivery
                EXCHANGE_STATS["columnar_frames_received"] += 1
                _process, scope_idx = self._owner(worker)
                batch = DeltaBatch.from_columns(
                    decode_columns_frame(frame),
                    consolidated=consolidated,
                    insert_only=insert_only,
                )
                batch._raw_insert_only = raw_insert_only
                if kind == "cstate":
                    # lazy replica-state apply: rows materialise only if a
                    # state-peeking consumer actually reads this replica
                    self.scopes[scope_idx].nodes[index]._defer_state(batch)
                else:
                    self.scopes[scope_idx].nodes[index].push(
                        port_or_worker, batch
                    )
                continue
            (
                kind, index, port_or_worker, worker, entries, consolidated,
                insert_only,
            ) = delivery
            _process, scope_idx = self._owner(worker)
            if kind == "state":
                self.scopes[scope_idx].nodes[index]._defer_state(
                    DeltaBatch(entries)
                )
            else:
                self._local_push(
                    scope_idx, index, port_or_worker, entries, consolidated,
                    insert_only,
                )
        return got

    def _stats_of(self, node: Node):
        from pathway_tpu.engine.graph import OperatorStats

        st = self.stats.get(node.index)
        if st is None:
            st = self.stats[node.index] = OperatorStats()
        return st

    def _metrics_snapshot(self) -> dict:
        """This process's registry snapshot plus its per-operator series —
        the payload followers piggyback on round frames bound for the
        leader (the mesh stats protocol).  When the sampling profiler is
        running, its payload rides along under the reserved
        ``"__profile__"`` key (popped by the leader at absorption, never
        rendered as a metrics family) — the frame arity stays at 8, so
        the PWC503 frame-shape contract is untouched."""
        snap = _metrics.full_snapshot(self)
        if _profiling.PROFILER.running:
            snap["__profile__"] = _profiling.PROFILER.payload()
        return snap

    # -- commit ------------------------------------------------------------

    def _drain_local(self, time: int) -> bool:
        """Process local pending work to quiescence (including same-time
        error-log feedback); remote parts accumulate in the outbox.
        Returns True if anything was processed."""
        busy = False
        probe = self.probe
        trace = _tracing.current()
        # traced runs attribute device-resident operator kernel time to
        # the launching span (same per-node split the sharded pump emits)
        _dops = None
        if trace is not None:
            from pathway_tpu.engine import device_ops as _device_ops

            if _device_ops.enabled():
                _dops = _device_ops
        while True:
            did = False
            busy_nodes = 0
            for scope_idx, scope in enumerate(self.scopes):
                for node in scope.nodes:
                    if not node.has_pending():
                        continue
                    did = True
                    busy_nodes += 1
                    if probe or trace is not None:
                        t0 = _walltime.perf_counter()
                    dns0 = _dops.total_ns() if _dops is not None else 0
                    out = node.process(time)
                    if out is None:
                        out = DeltaBatch()
                    out = out.consolidate() if out else out
                    # defer like the sharded scheduler: an eager apply
                    # would materialise columnar batches into rows before
                    # the vectorized exchange ships them
                    node._defer_state(out)
                    if trace is not None:
                        extra = {}
                        if _dops is not None:
                            dns = _dops.total_ns() - dns0
                            if dns:
                                extra["device_ns"] = dns
                        trace.span(
                            getattr(node, "name", None)
                            or type(node).__name__,
                            "sink"
                            if isinstance(node, SubscribeNode)
                            else "op",
                            t0,
                            _walltime.perf_counter(),
                            node=node.index,
                            scope=scope_idx,
                            **extra,
                        )
                    if probe:
                        st = self._stats_of(node)
                        st.time_spent += _walltime.perf_counter() - t0
                        st.batches += 1
                        st.last_time = time
                        cols = out.columns
                        if cols is not None:
                            if cols.diffs is None:
                                st.insertions += cols.n
                            else:
                                pos = int((cols.diffs > 0).sum())
                                st.insertions += pos
                                st.deletions += cols.n - pos
                        else:
                            for _k, _r, d in out.consolidate():
                                if d > 0:
                                    st.insertions += 1
                                else:
                                    st.deletions += 1
                    if out:
                        self._deliver(node, out, scope_idx)
            if probe:
                self._queue_gauge.value = float(busy_nodes)
            if did:
                busy = True
                continue
            flushed = False
            for scope in self.scopes:
                for node in scope.nodes:
                    if isinstance(node, ErrorLogNode):
                        batch = node.flush_buffer()
                        if batch:
                            node.push(0, batch)
                            flushed = True
            if not flushed:
                return busy
            busy = True

    def _flush_sources(self) -> None:
        """Coordinator: flush static sources + input sessions of the
        primary replica; maintain the sharded source-state invariant
        (sharded.py _route_source) and route downstream parts."""
        scope0 = self.scopes[0]
        for node in scope0.nodes:
            if isinstance(node, StaticSource):
                batch = node.initial_batch()
            elif isinstance(node, InputSession):
                batch = node.flush()
                if batch:
                    batch = batch.consolidate()  # flush may return raw diffs
            else:
                continue
            if not batch:
                continue
            # full state on the primary replica (lazily — the property
            # drains before anything reads it; sharded.py defers the same)
            node._defer_state(batch)
            if (
                COLUMNAR_EXCHANGE
                and batch._entries is not None
                and len(batch) >= VECTOR_THRESHOLD
            ):
                # bulk source commits enter the exchange as arrays: the
                # replica sharding and every consumer route below then run
                # the vectorized kernel + wire frames, not per-row hashing
                # (static sources arrive raw — consolidate first, since
                # the columnar twin asserts unique-key +1 invariants)
                cbatch = columnarize_entries(batch.consolidate())
                if cbatch is not None:
                    batch = cbatch
            # key-shard parts maintain replica state on workers > 0
            if self.n_workers > 1 and not self._replicate_source_columnar(
                node, batch
            ):
                parts: list[list] = [[] for _ in range(self.n_workers)]
                key_shards = shards_of_values(
                    [e[0] for e in batch.entries], self.n_workers
                )
                for e, w in zip(batch.entries, key_shards):
                    parts[w].append(e)
                for worker in range(1, self.n_workers):
                    if not parts[worker]:
                        continue
                    process, scope_idx = self._owner(worker)
                    if process == self.process_id:
                        self.scopes[scope_idx].nodes[
                            node.index
                        ]._defer_state(DeltaBatch(parts[worker]))
                    else:
                        self._push_remote(
                            process, "state", node.index, 0, worker,
                            parts[worker], batch._consolidated,
                        )
            self._deliver(node, batch)

    def _replicate_source_columnar(
        self, node: Node, batch: DeltaBatch
    ) -> bool:
        """Key-shard the source batch for replica state WITHOUT building
        per-row entries: same routing kernel, ``("key",)`` rule, state
        frames on the wire. False = caller runs the row loop."""
        if not (
            COLUMNAR_EXCHANGE
            and batch._entries is None
            and batch.columns is not None
        ):
            return False
        shards = columnar_shards(("key",), batch.columns, self.n_workers)
        if shards is None:
            return False
        cols = batch.columns
        workers = [w for w in np.unique(shards).tolist() if w != 0]
        if any(
            self._owner(w)[0] != self.process_id for w in workers
        ) and not _frame_encodable(cols):
            return False
        for worker in workers:
            part = cols.gather(np.flatnonzero(shards == worker))
            process, scope_idx = self._owner(worker)
            if process == self.process_id:
                self.scopes[scope_idx].nodes[node.index]._defer_state(
                    DeltaBatch.from_columns(
                        part, consolidated=batch._consolidated
                    )
                )
            else:
                frame = encode_columns_frame(part)
                assert frame is not None  # encodability proven above
                self._push_remote_columnar(
                    process, "cstate", node.index, 0, worker, frame,
                    batch._consolidated, False, False,
                )
        return True

    def _mark_replica_sources(self) -> None:
        """Non-primary replicas never emit static rows themselves
        (sharded.py: `if w != 0: node._emitted = True`)."""
        for scope_idx, scope in enumerate(self.scopes):
            if self.process_id == 0 and scope_idx == 0:
                continue
            for node in scope.nodes:
                if isinstance(node, StaticSource):
                    node._emitted = True

    def _recv_round(self, peer: int, time: int, round_no: int) -> tuple:
        """Receive one round frame from ``peer``, absorbing duplicated
        frames of the previous round (fault injection / resent links) and
        converting a peer's abort announcement into :class:`PeerLostError`
        so this process parks for recovery instead of deadlocking on a
        frame that will never come."""
        while True:
            frame = self.transport.recv(peer)
            kind = frame[0]
            if kind == "abort":
                raise PeerLostError(
                    f"process {self.process_id}: peer {peer} aborted "
                    f"commit {frame[1]} round {frame[2]} (its own peer "
                    "loss)",
                    peer=peer,
                )
            if kind == "cmd" and len(frame) >= 3 and frame[1] == "recover":
                if (
                    len(frame) >= 4
                    and frame[3] <= self.fence.floor("recover")
                ):
                    # fault-injected duplicate of a recovery we already
                    # ran: fenced, not re-triggered
                    self.fence.admit("recover", frame[3])
                    continue
                # the leader started recovery while this follower was
                # still waiting out the doomed round: stash the command
                # for the park loop and leave the round
                self._pending_recover = frame
                raise PeerLostError(
                    f"process {self.process_id}: leader announced "
                    f"recovery of peer {frame[2]} mid-round",
                    peer=frame[2],
                )
            if kind in ("sync", "rejoin", "elect", "elect-ack"):
                # recovery-era debris: a duplicated sync barrier frame or
                # a late election frame that survived the resync drain is
                # never legitimate inside a round — absorb it
                continue
            if kind == "round" and (
                frame[1] < time
                or (frame[1] == time and frame[2] < round_no)
            ):
                continue  # duplicate of a frame already applied
            return frame

    def _announce_abort(self, time: int, round_no: int) -> None:
        """Tell every still-reachable peer this process is leaving the
        round: survivors unblock immediately instead of waiting out the
        mesh timeout on a frame that will never arrive."""
        for peer in sorted(self._outbox):
            if peer in self.transport.dead_peers:
                continue
            try:
                self.transport._send(peer, ("abort", time, round_no))
            except OSError:
                pass

    def _exchange_rounds(self, time: int, notify_time_end: bool = True) -> bool:
        transport = self.transport
        peers = sorted(self._outbox)
        round_no = 0
        any_work = False
        try:
            while True:
                # re-fetched per round: a follower adopts the leader's
                # trace context from the round-0 frame, so rounds >= 1
                # (and the drain they gate) see it active
                ctx = _tracing.current()
                busy = self._drain_local(time)
                my_bit = busy or any(self._outbox.values())
                # mesh stats protocol: once this process goes quiet for the
                # round, piggyback its metrics snapshot on the frame bound
                # for the leader — no extra frames, no extra round-trips
                snap = None
                spans = None
                if self.process_id != 0 and not my_bit:
                    snap = self._metrics_snapshot()
                    # trace protocol, same shape: a quiet follower ships
                    # its span list to the leader; the last quiescent
                    # round carries the complete set (leader keeps the
                    # latest copy per peer)
                    if ctx is not None:
                        spans = ("spans", _tracing.TRACER.take_spans())
                trace_out = _tracing.TRACER.ctx_frame()
                hb = _walltime.time()
                for peer in peers:
                    transport.send(
                        peer,
                        (
                            "round", time, round_no, my_bit,
                            self._outbox[peer],
                            snap if peer == 0 else None,
                            hb,
                            trace_out if self.process_id == 0
                            else (spans if peer == 0 else None),
                        ),
                    )
                    self._outbox[peer] = []
                global_busy = my_bit
                for peer in peers:
                    if ctx is not None:
                        t0 = _walltime.perf_counter()
                    frame = self._recv_round(peer, time, round_no)
                    if ctx is not None:
                        # blocking on a peer's round frame is wire-exchange
                        # latency, not ingest queueing: it lands in the
                        # critical path's exchange bucket so the host-TCP
                        # exchange share is comparable against the device
                        # collective (engine/collective_exchange.py), which
                        # has no wire to wait on
                        ctx.span(
                            f"recv-wait:p{peer}",
                            "exchange",
                            t0,
                            _walltime.perf_counter(),
                            round=round_no,
                        )
                    (
                        kind, f_time, f_round, bit, deliveries, peer_snap,
                        peer_hb, trace_el,
                    ) = frame
                    if (
                        kind != "round"
                        or f_time != time
                        or f_round != round_no
                    ):
                        raise RuntimeError(
                            f"process {self.process_id}: protocol desync "
                            f"with peer {peer}: got {frame[:3]}, expected "
                            f"round ({time}, {round_no})"
                        )
                    if trace_el is not None:
                        if trace_el[0] == "ctx" and self.process_id != 0:
                            ctx = _tracing.TRACER.adopt(trace_el)
                        elif trace_el[0] == "spans" and self.process_id == 0:
                            self.trace_peer_spans[peer] = trace_el[1]
                    if ctx is not None and deliveries:
                        t0 = _walltime.perf_counter()
                        self._apply_remote(deliveries)
                        ctx.span(
                            f"apply:p{peer}",
                            "exchange",
                            t0,
                            _walltime.perf_counter(),
                            deliveries=len(deliveries),
                            round=round_no,
                        )
                    else:
                        self._apply_remote(deliveries)
                    if peer_snap is not None:
                        profile = peer_snap.pop("__profile__", None)
                        if profile is not None:
                            _profiling.PROFILER.absorb(peer, profile)
                        self.mesh_metrics[peer] = peer_snap
                    self.peer_heartbeats[peer] = peer_hb
                    global_busy = global_busy or bit
                round_no += 1
                any_work = any_work or global_busy
                if not global_busy:
                    break
        except PeerLostError:
            self._announce_abort(time, round_no)
            raise
        _metrics.FLIGHT.record("exchange", time=time, rounds=round_no)
        if notify_time_end or any_work:
            for scope in self.scopes:
                for node in scope.nodes:
                    node.on_time_end(time)
        from pathway_tpu.engine import device_pipeline

        device_pipeline.commit_boundary(time)
        return any_work

    def commit_local(self) -> int:
        """One commit: coordinator flushes sources, then all processes run
        exchange rounds to global quiescence."""
        self._ensure_optimized()  # no-op after the topology handshake
        self._mark_replica_sources()
        if self.process_id == 0:
            self._flush_sources()
        time = self.time
        self._exchange_rounds(time)
        self.time += 1
        _metrics.FLIGHT.record(
            "commit", time=time, process=self.process_id
        )
        if self.process_id != 0:
            # adopted context ends with the commit; its spans already
            # rode the final quiescent round's frame to the leader
            _tracing.TRACER.drop()
        return time

    def finish_local(self) -> None:
        """Final commit + on_end hooks + one settling commit
        (ShardedScheduler.finish)."""
        self.commit_local()
        for scope in self.scopes:
            for node in scope.nodes:
                node.on_end()
        # on_end may inject final batches (buffer flush) on any process;
        # sinks tear down in close() only after the settlement delivers them
        self._exchange_rounds(self.time, notify_time_end=False)
        self.time += 1
        if self.process_id != 0:
            _tracing.TRACER.drop()
        from pathway_tpu.engine import device_pipeline

        device_pipeline.drain()
        for scope in self.scopes:
            for node in scope.nodes:
                node.close()

    # -- recovery ----------------------------------------------------------

    def discard_inflight(self) -> None:
        """Drop every runtime-queued batch on this process — operator
        pending queues, deferred state lag, unflushed input-session rows,
        and the remote outbox.  Run before a snapshot rollback: anything
        in flight belongs to a commit the rollback un-happens, and the
        restored snapshot (plus re-driven connectors) re-derives it."""
        from pathway_tpu.engine import device_pipeline

        device_pipeline.reset()
        for scope in self.scopes:
            for node in scope.nodes:
                node.pending.clear()
                node._state_lag = []
                node._state_lag_rows = 0
                if isinstance(node, InputSession):
                    node._buffer = []
                    node._has_removals = False
                    node._has_rowless_removals = False
        for peer in self._outbox:
            self._outbox[peer] = []

    def prune_mesh_metrics(self, dead: Sequence[int] = ()) -> None:
        """Drop piggybacked metrics snapshots (and pending trace spans)
        of peers that no longer exist: explicitly named dead peers, the
        transport's dead set, and ids beyond the current mesh width —
        so the aggregated ``/metrics`` exposition stops rendering their
        ``worker=`` label sets."""
        gone = set(dead) | set(self.transport.dead_peers)
        for peer in list(self.mesh_metrics):
            if peer in gone or peer >= self.n_processes:
                self.mesh_metrics.pop(peer, None)
        for peer in list(self.trace_peer_spans):
            if peer in gone or peer >= self.n_processes:
                self.trace_peer_spans.pop(peer, None)
        # same lifecycle for the other observability planes: absorbed
        # profile payloads and the timeseries ring's worker label sets
        # of dead/out-of-width peers must not outlive them
        _profiling.PROFILER.prune(dead=gone, width=self.n_processes)
        _timeseries.STORE.prune_workers(
            dead={str(p) for p in gone}, width=self.n_processes
        )

    def resync(self, epoch: int) -> None:
        """Post-rollback barrier: flush stale frames off every peer link.
        Each process sends ``("sync", epoch)`` to every peer, then drains
        each peer queue until the matching sync arrives — per-peer FIFO
        ordering guarantees everything queued before it (orphaned round
        frames, aborts, old syncs) is gone.  All sends precede all drains,
        so the barrier cannot deadlock even with bounded queues."""
        # raise the trace fence with the mesh epoch: context tuples a
        # fenced-out zombie leader stamped before this barrier are
        # rejected by TraceRecorder.adopt; the profiler fence rises in
        # lockstep so pre-barrier profile payloads are dropped too
        _tracing.TRACER.epoch = max(_tracing.TRACER.epoch, int(epoch))
        _profiling.PROFILER.epoch = max(
            _profiling.PROFILER.epoch, int(epoch)
        )
        from pathway_tpu import serving as _serving

        if _serving.enabled():
            # the snapshot stream rises in lockstep too: replicas fence
            # out any ``snap`` frame a zombie publisher stamped before
            # this barrier (PWC504 semantics on the read tier)
            _serving.set_stream_epoch(int(epoch))
        peers = sorted(self._outbox)
        for peer in peers:
            self.transport.send(peer, ("sync", epoch))
        for peer in peers:
            while True:
                frame = self.transport.recv(peer)
                if (
                    isinstance(frame, tuple)
                    and frame
                    and frame[0] == "sync"
                    and frame[1] == epoch
                ):
                    break

    def rollback(self, to_time: int, snapshot_mgr, drivers: list) -> None:
        """Roll this process back to the snapshot of commit ``to_time``
        (``-1`` = cold state) and resume the clock after it.  The caller
        runs :meth:`resync` afterwards so every peer crosses the same
        epoch boundary before new rounds begin."""
        self.discard_inflight()
        if to_time >= 0:
            restored = snapshot_mgr.restore(
                self.scopes, drivers, at_time=to_time
            )
            self.time = int(restored) + 1
        else:
            self.time = max(self.time, 0)
        _metrics.FLIGHT.record(
            "recovery_rollback",
            process=self.process_id,
            to_time=to_time,
            resumed_time=self.time,
        )
        from pathway_tpu import serving as _serving

        if _serving.enabled():
            # Readers must never observe commits the mesh rolled back
            # past; publish() self-heals at the next commit, but the
            # window between rollback and re-commit would otherwise
            # serve retracted state.  truncate() also invalidates the
            # commit-stamped result cache above ``to_time`` (commit
            # times are re-used with different content after recovery)
            # and stream_truncate() fans the same command out to every
            # subscribed replica as an epoch-fenced ``snap-rollback``.
            _serving.STORE.truncate(to_time)
            _serving.stream_truncate(to_time)

    # -- monitoring surface parity ----------------------------------------

    @property
    def scope(self) -> Scope:
        return self.scopes[0]

    def merged_state(self, index: int) -> dict[Pointer, tuple]:
        """Union of one operator's state across LOCAL replicas (cross-
        process captures are not collected; outputs flow through sinks)."""
        out: dict[Pointer, tuple] = {}
        for scope in self.scopes:
            out.update(scope.nodes[index].current)
        return out
