"""Delta batches — the unit of incremental data movement.

Every engine table is a keyed update stream: batches of ``(key, row, diff)``
at a logical timestamp (reference: tables as
``Collection<S, (Key, Value)>`` diffs, src/engine/dataflow.rs:820). A batch is
consolidated when each (key, row) appears once with a non-zero diff.

Rows are plain tuples of engine values; columnar views (NumPy / DLPack →
jax.Array) are materialized on demand by the device bridge
(:mod:`pathway_tpu.engine.device`).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator

from pathway_tpu.engine.value import Pointer

Entry = tuple[Pointer, tuple, int]


class DeltaBatch:
    """A consolidatable batch of keyed row updates."""

    __slots__ = ("entries",)

    def __init__(self, entries: Iterable[Entry] | None = None) -> None:
        self.entries: list[Entry] = list(entries) if entries is not None else []

    def append(self, key: Pointer, row: tuple, diff: int) -> None:
        if diff != 0:
            self.entries.append((key, row, diff))

    def extend(self, entries: Iterable[Entry]) -> None:
        for key, row, diff in entries:
            if diff != 0:
                self.entries.append((key, row, diff))

    def __iter__(self) -> Iterator[Entry]:
        return iter(self.entries)

    def __len__(self) -> int:
        return len(self.entries)

    def __bool__(self) -> bool:
        return bool(self.entries)

    def __repr__(self) -> str:
        return f"DeltaBatch({self.entries!r})"

    def consolidate(self) -> "DeltaBatch":
        """Merge duplicate (key, row) entries, dropping zero diffs."""
        acc: dict[tuple[Pointer, int], list[Any]] = {}
        order: list[tuple[Pointer, int]] = []
        for key, row, diff in self.entries:
            try:
                slot = (key, hash(row))
            except TypeError:
                slot = (key, id(row))
            found = acc.get(slot)
            if found is None:
                acc[slot] = [row, diff]
                order.append(slot)
            else:
                found[1] += diff
        out = DeltaBatch()
        for slot in order:
            row, diff = acc[slot]
            if diff != 0:
                out.entries.append((slot[0], row, diff))
        return out

    def map_rows(self, fn: Callable[[Pointer, tuple], tuple]) -> "DeltaBatch":
        return DeltaBatch((key, fn(key, row), diff) for key, row, diff in self.entries)

    def negated(self) -> "DeltaBatch":
        return DeltaBatch((key, row, -diff) for key, row, diff in self.entries)


def apply_batch_to_state(state: dict[Pointer, tuple], batch: DeltaBatch) -> None:
    """Apply a consolidated batch of ±1-style updates to a key→row map.

    A table maps each key to exactly one row; an in-place update arrives as
    a retraction of the old row and an insertion of the new one.
    """
    removed: dict[Pointer, tuple] = {}
    for key, row, diff in batch:
        if diff < 0:
            for _ in range(-diff):
                prev = state.pop(key, None)
                if prev is not None:
                    removed[key] = prev
    for key, row, diff in batch:
        if diff > 0:
            state[key] = row
