"""Delta batches — the unit of incremental data movement.

Every engine table is a keyed update stream: batches of ``(key, row, diff)``
at a logical timestamp (reference: tables as
``Collection<S, (Key, Value)>`` diffs, src/engine/dataflow.rs:820). A batch is
consolidated when each (key, row) appears once with a non-zero diff.

Two physical representations share the :class:`DeltaBatch` interface:

- **row form** — a list of ``(Pointer, tuple, int)`` entries; the universal
  fallback every operator understands.
- **columnar form** — a :class:`Columns` payload: keys as a ``(n, 16)``
  little-endian byte matrix (or an object vector of Pointers), one NumPy
  array per column, and an optional diff vector (``None`` = all +1).
  Produced by the vectorized operator paths (expression eval, filter,
  hash join, groupby) and consumed array-to-array downstream; rows
  materialise lazily only when something row-oriented touches the batch.

The columnar form is what lets the engine hot path clear the ~1µs/row
Python-object floor: a bulk commit flows source → join → groupby as NumPy
gathers plus one vectorized key-hash pass, with zero per-row PyObjects
unless a sink or a state read asks for them.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Iterable, Iterator, Sequence

import numpy as np

from pathway_tpu.engine.value import Pointer
from pathway_tpu.native import kernels as _native

Entry = tuple[Pointer, tuple, int]


class Columns:
    """Columnar payload of a delta batch.

    ``kbytes`` and ``kobjs`` are two views of the same keys — 16-byte
    little-endian rows vs. Pointer objects; either may be absent and is
    derived from the other on demand. ``cols`` holds one 1-D array per
    column (clean dtypes where possible, ``object`` otherwise). ``diffs``
    is ``None`` when every diff is +1.
    """

    __slots__ = ("n", "_kbytes", "_kobjs", "_kb_thunk", "cols", "diffs")

    def __init__(
        self,
        n: int,
        cols: Sequence[np.ndarray],
        kbytes: np.ndarray | None = None,
        kobjs: Sequence[Pointer] | None = None,
        diffs: np.ndarray | None = None,
        kb_thunk: "Any | None" = None,
    ) -> None:
        assert kbytes is not None or kobjs is not None or kb_thunk is not None
        self.n = n
        self._kbytes = kbytes
        self._kobjs = list(kobjs) if kobjs is not None else None
        #: lazy key derivation (e.g. the join's vectorized BLAKE2b pair
        #: hash): runs only when somebody actually observes the keys
        self._kb_thunk = kb_thunk
        self.cols = list(cols)
        self.diffs = diffs

    # -- key views ----------------------------------------------------------

    def kbytes(self) -> np.ndarray:
        """Keys as a C-contiguous (n, 16) uint8 little-endian matrix."""
        if self._kbytes is None:
            if self._kb_thunk is not None:
                self._kbytes = self._kb_thunk()
                self._kb_thunk = None
                return self._kbytes
            kb = None
            if _native is not None and hasattr(_native, "pointers_to_bytes"):
                kb = _native.pointers_to_bytes(self._kobjs)
            if kb is None:
                buf = b"".join(
                    int(k).to_bytes(16, "little") for k in self._kobjs
                )
                kb = np.frombuffer(buf, np.uint8).reshape(self.n, 16)
            self._kbytes = kb
        return self._kbytes

    def kobjs(self) -> list[Pointer]:
        """Keys as Pointer objects (materialised once, then cached)."""
        if self._kobjs is None:
            kb = np.ascontiguousarray(self.kbytes())
            if _native is not None and hasattr(_native, "bytes_to_pointers"):
                self._kobjs = _native.bytes_to_pointers(kb, Pointer)
            else:
                mem = kb.tobytes()
                self._kobjs = [
                    Pointer(int.from_bytes(mem[i * 16 : i * 16 + 16], "little"))
                    for i in range(self.n)
                ]
        return self._kobjs

    # -- transforms ---------------------------------------------------------

    def gather(self, idx: np.ndarray) -> "Columns":
        """Row subset/reorder by an index vector (NumPy fancy gather)."""
        kb = self._kbytes
        kobjs = None
        if kb is None and self._kb_thunk is not None:
            kb = self.kbytes()  # force the lazy keys once
        if kb is not None:
            kb = kb[idx]
        else:
            arr = np.empty(self.n, object)
            arr[:] = self._kobjs
            kobjs = arr[idx].tolist()
        diffs = self.diffs[idx] if self.diffs is not None else None
        return Columns(
            int(len(idx)),
            [c[idx] for c in self.cols],
            kbytes=kb,
            kobjs=kobjs,
            diffs=diffs,
        )

    def keys_gather(
        self, idx: np.ndarray
    ) -> "tuple[np.ndarray | None, list | None]":
        """Key rows at ``idx`` as ``(kbytes, kobjs)`` — exactly one is
        non-None — without touching the value columns (the fused-chain
        sweep pairs surviving keys with freshly evaluated arrays)."""
        kb = self._kbytes
        if kb is None and self._kb_thunk is not None:
            kb = self.kbytes()  # force the lazy keys once
        if kb is not None:
            return kb[idx], None
        arr = np.empty(self.n, object)
        arr[:] = self._kobjs
        return None, arr[idx].tolist()

    def compress(self, mask: np.ndarray) -> "Columns":
        """Row subset by boolean mask."""
        return self.gather(np.flatnonzero(mask))

    def column_diffs(self) -> np.ndarray:
        """Diff vector (materialising the implicit all-ones case)."""
        if self.diffs is None:
            return np.ones(self.n, np.int64)
        return self.diffs

    @classmethod
    def with_keys_of(
        cls,
        other: "Columns",
        cols: Sequence[np.ndarray],
        diffs: np.ndarray | None = None,
    ) -> "Columns":
        """New payload sharing ``other``'s key storage (zero-copy — keys
        are immutable); used by key-preserving operators (select/filter)."""
        c = cls.__new__(cls)
        c.n = other.n
        c._kbytes = other._kbytes
        c._kobjs = other._kobjs
        # a still-lazy source: route through other.kbytes so the thunk
        # runs once and caches in the source
        c._kb_thunk = (
            other.kbytes
            if other._kbytes is None and other._kobjs is None
            else None
        )
        c.cols = list(cols)
        c.diffs = diffs
        return c

    @classmethod
    def concat(cls, parts: "Sequence[Columns]") -> "Columns | None":
        """Stack columnar payloads row-wise, or None when layouts differ
        (arity mismatch or any per-column dtype mismatch — silent NumPy
        promotion would change materialised Python types)."""
        arity = len(parts[0].cols)
        if any(len(p.cols) != arity for p in parts[1:]):
            return None
        for c in range(arity):
            dt = parts[0].cols[c].dtype
            if any(p.cols[c].dtype != dt for p in parts[1:]):
                return None
        n = sum(p.n for p in parts)
        cols = [
            np.concatenate([p.cols[c] for p in parts])
            for c in range(arity)
        ]
        if all(
            p._kbytes is not None or p._kb_thunk is not None for p in parts
        ):
            if any(p._kbytes is None for p in parts):
                # keep laziness across the concat, pinning only each
                # part's KEY source — not the whole Columns (the output
                # already owns fresh copies of every data column)
                sources = [
                    p._kb_thunk if p._kbytes is None else p._kbytes
                    for p in parts
                ]
                return cls(
                    n,
                    cols,
                    kb_thunk=lambda: np.concatenate(
                        [s() if callable(s) else s for s in sources]
                    ),
                    diffs=(
                        None
                        if all(p.diffs is None for p in parts)
                        else np.concatenate(
                            [p.column_diffs() for p in parts]
                        )
                    ),
                )
            kbytes = np.concatenate([p._kbytes for p in parts])
            kobjs = None
        else:
            kbytes = None
            kobjs = [k for p in parts for k in p.kobjs()]
        if all(p.diffs is None for p in parts):
            diffs = None
        else:
            diffs = np.concatenate([p.column_diffs() for p in parts])
        return cls(n, cols, kbytes=kbytes, kobjs=kobjs, diffs=diffs)

    def to_entries(self) -> list[Entry]:
        """Materialise row-form entries (the per-row object cost lives
        here, paid only when a row-oriented consumer needs it)."""
        keys = self.kobjs()
        if _native is not None and hasattr(_native, "columns_to_entries"):
            diffs = self.diffs
            if diffs is not None:
                diffs = np.ascontiguousarray(diffs, np.int64)
            return _native.columns_to_entries(
                keys, [np.ascontiguousarray(c) for c in self.cols], diffs
            )
        if self.cols:
            rows = zip(*[c.tolist() for c in self.cols])
        else:
            rows = ((),) * self.n
        if self.diffs is None:
            return [(k, r, 1) for k, r in zip(keys, rows)]
        return [
            (k, r, int(d)) for k, r, d in zip(keys, rows, self.diffs)
        ]


class DeltaBatch:
    """A consolidatable batch of keyed row updates."""

    __slots__ = (
        "_entries",
        "columns",
        "_consolidated",
        "_insert_only",
        "_raw_insert_only",
        "_ccache",
    )

    def __init__(self, entries: Iterable[Entry] | None = None) -> None:
        self._entries: list[Entry] | None = (
            list(entries) if entries is not None else []
        )
        self.columns: Columns | None = None
        self._consolidated = False
        self._insert_only = False  # set by consolidate(): unique-key inserts
        #: producer guarantees every diff is literally +1 (session inserts,
        #: static rows) WITHOUT the key-uniqueness scan of consolidate().
        #: Multiset-correct consumers (the columnar join) accept this hint;
        #: dict-state consumers still consolidate.
        self._raw_insert_only = False
        #: cached consolidate() result — a batch fanning out to several
        #: consumers (each consolidating in take()) merges only once
        self._ccache: "DeltaBatch | None" = None

    @classmethod
    def from_columns(
        cls,
        columns: Columns,
        consolidated: bool = True,
        insert_only: bool = False,
    ) -> "DeltaBatch":
        """Wrap a columnar payload; producers assert consolidation
        invariants at construction (unique keys ⇒ consolidated)."""
        out = cls.__new__(cls)
        out._entries = None
        out.columns = columns
        out._consolidated = consolidated
        out._insert_only = insert_only and columns.diffs is None
        out._raw_insert_only = out._insert_only
        out._ccache = None
        return out

    @property
    def entries(self) -> list[Entry]:
        if self._entries is None:
            self._entries = self.columns.to_entries()
        return self._entries

    @entries.setter
    def entries(self, value: list[Entry]) -> None:
        self._entries = value
        self.columns = None
        self._ccache = None
        self._raw_insert_only = False

    def append(self, key: Pointer, row: tuple, diff: int) -> None:
        if diff != 0:
            entries = self._entries
            if entries is None:
                entries = self.entries
            entries.append((key, row, diff))
            self.columns = None  # row mutation invalidates the columnar view
            self._consolidated = False
            self._insert_only = False
            self._raw_insert_only = False
            self._ccache = None

    def extend(self, entries: Iterable[Entry]) -> None:
        target = self.entries
        appended = False
        for key, row, diff in entries:
            if diff != 0:
                target.append((key, row, diff))
                appended = True
        if appended:
            self.columns = None
            self._consolidated = False
            self._insert_only = False
            self._raw_insert_only = False
            self._ccache = None

    def __iter__(self) -> Iterator[Entry]:
        return iter(self.entries)

    def __len__(self) -> int:
        if self._entries is not None:
            return len(self._entries)
        return self.columns.n

    def __bool__(self) -> bool:
        return len(self) > 0

    def __repr__(self) -> str:
        if self._entries is None:
            return f"DeltaBatch(<columnar n={self.columns.n}>)"
        return f"DeltaBatch({self._entries!r})"

    def consolidate(self) -> "DeltaBatch":
        """Merge duplicate (key, row) entries, dropping zero diffs."""
        if self._consolidated:
            return self
        if self._ccache is not None:
            return self._ccache
        if self._entries is None:
            got = self._consolidate_columns()
            if got is not None:
                return got
            # no vectorized merge for this payload (object columns,
            # NaN/-0.0 floats, unfetchable keys) — materialise rows
            self.entries  # noqa: B018 — force row form
        if _native is not None:
            merged, insert_only = _native.consolidate(self._entries)
            if merged is None:  # precheck passed: already consolidated
                self._consolidated = True
                self._insert_only = insert_only
                return self
            out = DeltaBatch()
            out._entries = merged
            out._consolidated = True
            self._ccache = out
            return out
        # Cheap precheck for the dominant shape — insert-only with unique
        # keys (connector ingest, expression outputs): key uniqueness alone
        # implies (key, row) uniqueness, so the batch is already consolidated.
        seen: set = set()
        seen_add = seen.add
        clean = True
        for key, _row, diff in self._entries:
            if diff <= 0 or key in seen:
                clean = False
                break
            seen_add(key)
        if clean:
            self._consolidated = True
            self._insert_only = True
            return self
        acc: dict[tuple[Pointer, Any], list[Any]] = {}
        order: list[tuple[Pointer, Any]] = []
        for key, row, diff in self._entries:
            try:
                hash(row)
                slot = (key, row)  # dict handles hash + equality correctly
            except TypeError:
                slot = (key, id(row))
            found = acc.get(slot)
            if found is None:
                acc[slot] = [row, diff]
                order.append(slot)
            else:
                found[1] += diff
        out = DeltaBatch()
        for slot in order:
            row, diff = acc[slot]
            if diff != 0:
                out._entries.append((slot[0], row, diff))
        out._consolidated = True
        self._ccache = out
        return out

    def _consolidate_columns(self) -> "DeltaBatch | None":
        """Vectorized consolidate for a columnar payload — merge duplicate
        (key, row) rows without ever materialising row tuples, or ``None``
        when bit equality and value equality could diverge (object columns,
        NaN or -0.0 in a float column) or the keys cannot be fetched.

        Identity matches the row path exactly: within a uniform-dtype
        column, bit equality IS value equality once NaN (never equal) and
        -0.0 (equal to +0.0 but bit-distinct) are excluded, so a structured
        view over (key bytes, columns) groups precisely the rows the dict
        slot ``(key, row)`` would merge."""
        cols = self.columns
        n = cols.n
        if n == 0:
            self._consolidated = True
            self._insert_only = True
            return self
        for c in cols.cols:
            if c.dtype.kind not in "bifU":
                return None
            if c.dtype.kind == "f" and (
                np.isnan(c).any() or np.signbit(c[c == 0]).any()
            ):
                return None
        try:
            kb = np.ascontiguousarray(cols.kbytes())
        except Exception:
            return None
        diffs = cols.diffs
        # precheck mirroring the row path: positive diffs + unique keys
        # means there is nothing to merge — flag in place, copy nothing
        if diffs is None or (diffs > 0).all():
            lo = np.sort(kb[:, :8].view(np.uint64).ravel())
            if not (lo[1:] == lo[:-1]).any() or len(
                np.unique(kb.view(np.dtype((np.void, 16))).ravel())
            ) == n:
                self._consolidated = True
                self._insert_only = True
                return self
        rec = np.empty(
            n,
            dtype=[("k", (np.void, 16))]
            + [(f"c{i}", c.dtype) for i, c in enumerate(cols.cols)],
        )
        rec["k"] = kb.view(np.dtype((np.void, 16))).ravel()
        for i, c in enumerate(cols.cols):
            rec[f"c{i}"] = c
        _uniq, first, inverse = np.unique(
            rec, return_index=True, return_inverse=True
        )
        sums = np.zeros(len(first), np.int64)
        np.add.at(
            sums,
            inverse.ravel(),
            np.int64(1) if diffs is None else diffs,
        )
        order = np.argsort(first, kind="stable")  # first-seen entry order
        keep = sums[order] != 0
        idx = first[order][keep]
        newdiffs = sums[order][keep]
        merged = Columns(
            int(len(idx)),
            [c[idx] for c in cols.cols],
            kbytes=kb[idx],
            diffs=None if (newdiffs == 1).all() else newdiffs,
        )
        out = DeltaBatch.from_columns(
            merged, consolidated=True, insert_only=False
        )
        self._ccache = out
        return out

    def map_rows(self, fn: Callable[[Pointer, tuple], tuple]) -> "DeltaBatch":
        return DeltaBatch((key, fn(key, row), diff) for key, row, diff in self.entries)

    def negated(self) -> "DeltaBatch":
        return DeltaBatch((key, row, -diff) for key, row, diff in self.entries)


def columnarize_entries(batch: DeltaBatch) -> DeltaBatch | None:
    """Columnar twin of a consolidated insert-only row batch, or None.

    Sources flush row entries, but the exchange seams (engine/sharded.py,
    engine/distributed.py) route and serialize arrays: one columnarisation
    pass here lets a bulk source commit take the vectorized routing kernel
    and the dtype-tagged wire frames instead of per-row hashing and row
    pickles. Requires Pointer keys and uniform row arity; mixed-type
    columns degrade to exact-object arrays (still key-routable, though not
    wire-frame encodable). ``consolidated + insert_only`` is demanded up
    front because ``from_columns`` asserts those invariants.
    """
    if not (batch._consolidated and batch._insert_only):
        return None
    entries = batch._entries
    if not entries:
        return None
    # all-C arity scan (map/set run the loop without Python frames): a
    # ragged batch must stay row-form — the columnar twin would silently
    # truncate long rows to the first row's arity
    if len(set(map(len, map(operator.itemgetter(1), entries)))) != 1:
        return None
    arity = len(entries[0][1])
    kb = None
    if _native is not None:
        kb = _native.entry_keys_bytes(entries, Pointer)
    else:
        if all(type(e[0]) is Pointer for e in entries):
            buf = b"".join(
                int(e[0]).to_bytes(16, "little") for e in entries
            )
            kb = np.frombuffer(buf, np.uint8).reshape(len(entries), 16)
    if kb is None:
        return None  # non-Pointer keys: row path
    from pathway_tpu.engine import device

    view = device.ColumnarView(entries, from_entries=True)
    return DeltaBatch.from_columns(
        Columns(len(entries), device.materialize_columns(view, arity), kbytes=kb),
        consolidated=True,
        insert_only=True,
    )


def apply_batch_to_state(state: dict[Pointer, tuple], batch: DeltaBatch) -> None:
    """Apply a consolidated batch of ±1-style updates to a key→row map.

    A table maps each key to exactly one row; an in-place update arrives as
    a retraction of the old row and an insertion of the new one.
    """
    entries = batch.entries
    if _native is not None:
        _native.apply_state(state, entries, batch._insert_only)
        return
    if batch._insert_only:
        # C-speed bulk store: no retraction pass needed
        state.update((key, row) for key, row, _d in entries)
        return
    for key, row, diff in entries:
        if diff < 0:
            state.pop(key, None)
    for key, row, diff in entries:
        if diff > 0:
            state[key] = row
