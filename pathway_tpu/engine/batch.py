"""Delta batches — the unit of incremental data movement.

Every engine table is a keyed update stream: batches of ``(key, row, diff)``
at a logical timestamp (reference: tables as
``Collection<S, (Key, Value)>`` diffs, src/engine/dataflow.rs:820). A batch is
consolidated when each (key, row) appears once with a non-zero diff.

Rows are plain tuples of engine values; columnar views (NumPy / DLPack →
jax.Array) are materialized on demand by the device bridge
(:mod:`pathway_tpu.engine.device`).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator

from pathway_tpu.engine.value import Pointer
from pathway_tpu.native import kernels as _native

Entry = tuple[Pointer, tuple, int]


class DeltaBatch:
    """A consolidatable batch of keyed row updates."""

    __slots__ = ("entries", "_consolidated", "_insert_only", "_preapplied")

    def __init__(self, entries: Iterable[Entry] | None = None) -> None:
        self.entries: list[Entry] = list(entries) if entries is not None else []
        self._consolidated = False
        self._insert_only = False  # set by consolidate(): unique-key inserts
        #: producer already wrote these rows into its own node state
        #: (fused C kernels); only the PRODUCING node's apply is skipped —
        #: flag never travels on delivered/copied batches
        self._preapplied = False

    def append(self, key: Pointer, row: tuple, diff: int) -> None:
        if diff != 0:
            self.entries.append((key, row, diff))
            self._consolidated = False
            self._insert_only = False

    def extend(self, entries: Iterable[Entry]) -> None:
        appended = False
        for key, row, diff in entries:
            if diff != 0:
                self.entries.append((key, row, diff))
                appended = True
        if appended:
            self._consolidated = False
            self._insert_only = False

    def __iter__(self) -> Iterator[Entry]:
        return iter(self.entries)

    def __len__(self) -> int:
        return len(self.entries)

    def __bool__(self) -> bool:
        return bool(self.entries)

    def __repr__(self) -> str:
        return f"DeltaBatch({self.entries!r})"

    def consolidate(self) -> "DeltaBatch":
        """Merge duplicate (key, row) entries, dropping zero diffs."""
        if self._consolidated:
            return self
        if _native is not None:
            merged, insert_only = _native.consolidate(self.entries)
            if merged is None:  # precheck passed: already consolidated
                self._consolidated = True
                self._insert_only = insert_only
                return self
            out = DeltaBatch()
            out.entries = merged
            out._consolidated = True
            return out
        # Cheap precheck for the dominant shape — insert-only with unique
        # keys (connector ingest, expression outputs): key uniqueness alone
        # implies (key, row) uniqueness, so the batch is already consolidated.
        seen: set = set()
        seen_add = seen.add
        clean = True
        for key, _row, diff in self.entries:
            if diff <= 0 or key in seen:
                clean = False
                break
            seen_add(key)
        if clean:
            self._consolidated = True
            self._insert_only = True
            return self
        acc: dict[tuple[Pointer, Any], list[Any]] = {}
        order: list[tuple[Pointer, Any]] = []
        for key, row, diff in self.entries:
            try:
                hash(row)
                slot = (key, row)  # dict handles hash + equality correctly
            except TypeError:
                slot = (key, id(row))
            found = acc.get(slot)
            if found is None:
                acc[slot] = [row, diff]
                order.append(slot)
            else:
                found[1] += diff
        out = DeltaBatch()
        for slot in order:
            row, diff = acc[slot]
            if diff != 0:
                out.entries.append((slot[0], row, diff))
        out._consolidated = True
        return out

    def map_rows(self, fn: Callable[[Pointer, tuple], tuple]) -> "DeltaBatch":
        return DeltaBatch((key, fn(key, row), diff) for key, row, diff in self.entries)

    def negated(self) -> "DeltaBatch":
        return DeltaBatch((key, row, -diff) for key, row, diff in self.entries)


def apply_batch_to_state(state: dict[Pointer, tuple], batch: DeltaBatch) -> None:
    """Apply a consolidated batch of ±1-style updates to a key→row map.

    A table maps each key to exactly one row; an in-place update arrives as
    a retraction of the old row and an insertion of the new one.
    """
    if batch._preapplied:
        batch._preapplied = False  # one producing-node apply only
        return
    if _native is not None:
        _native.apply_state(state, batch.entries, batch._insert_only)
        return
    if batch._insert_only:
        # C-speed bulk store: no retraction pass needed
        state.update((key, row) for key, row, _d in batch.entries)
        return
    for key, row, diff in batch:
        if diff < 0:
            state.pop(key, None)
    for key, row, diff in batch:
        if diff > 0:
            state[key] = row
