"""Connector framework: Reader → Parser → InputSession and
Subscribe → Formatter → Writer.

New implementation of the reference connector subsystem
(reference: src/connectors/mod.rs:428 `Connector::run` pull loop,
data_storage.rs Reader/Writer traits :372/:600, data_format.rs
Parser/Formatter traits :262/:452). The reference spawns one thread per
source plus a poller closure stepped by the worker loop; here each source is
an :class:`InputDriver` polled by the streaming run loop between commits —
same contract (bounded batches per commit, commit timestamps), simpler
machinery. Python push-sources use a thread + queue like the reference's
PythonSubject (python_api.rs PythonSubject).
"""

from __future__ import annotations

import csv as _csv
import glob as _glob
import io as _io
import json as _json
import os
import queue
import threading
import time as _time
from typing import Any, Callable, Iterable, Sequence

from pathway_tpu.engine.graph import InputSession, Node, Scope
from pathway_tpu.engine.value import Json, Pointer, hash_values, ref_scalar
from pathway_tpu.internals import metrics as _metrics

# -- parsed events ----------------------------------------------------------

INSERT = "insert"
DELETE = "delete"
UPSERT = "upsert"


class ParsedEvent:
    """``key`` is an optional tuple of key values (CDC streams carry the row
    identity explicitly); ``values`` may be None for an upsert deletion
    (reference ParsedEvent Insert/Delete/Upsert, data_format.rs:175)."""

    __slots__ = ("kind", "values", "key")

    def __init__(
        self, kind: str, values: tuple | None, key: tuple | None = None
    ) -> None:
        self.kind = kind
        self.values = values
        self.key = key


# -- parsers ----------------------------------------------------------------


class Parser:
    """payload (str/bytes) → list of ParsedEvent with values in schema order.

    ``session_type`` mirrors the reference's Parser::session_type
    (data_format.rs:262): "native" feeds insert/remove diffs, "upsert"
    feeds an overlay session keyed by the event key.
    """

    session_type = "native"

    def __init__(self, column_names: Sequence[str]) -> None:
        self.column_names = list(column_names)

    def parse(self, payload: Any) -> list[ParsedEvent]:
        raise NotImplementedError


class DsvParser(Parser):
    """Delimiter-separated values with a header row (reference: DsvParser
    data_format.rs:500)."""

    def __init__(
        self,
        column_names: Sequence[str],
        converters: Sequence[Callable[[str], Any]] | None = None,
        delimiter: str = ",",
    ) -> None:
        super().__init__(column_names)
        self.delimiter = delimiter
        self.converters = list(converters) if converters else None
        self._header: list[str] | None = None

    def reset(self) -> None:
        self._header = None

    def parse(self, payload: str) -> list[ParsedEvent]:
        rows = list(_csv.reader(_io.StringIO(payload), delimiter=self.delimiter))
        if not rows:
            return []
        events = []
        start = 0
        if self._header is None:
            self._header = [h.strip() for h in rows[0]]
            start = 1
        positions = [self._header.index(c) for c in self.column_names]
        for row in rows[start:]:
            if not row:
                continue
            raw = tuple(row[p] if p < len(row) else "" for p in positions)
            if self.converters:
                values = tuple(conv(v) for conv, v in zip(self.converters, raw))
            else:
                values = raw
            events.append(ParsedEvent(INSERT, values))
        return events


class JsonLinesParser(Parser):
    """One JSON object per line (reference: JsonLinesParser data_format.rs:1439)."""

    def __init__(
        self, column_names: Sequence[str], defaults: dict[str, Any] | None = None
    ) -> None:
        super().__init__(column_names)
        self.defaults = defaults or {}

    def parse(self, payload: str) -> list[ParsedEvent]:
        events = []
        for line in payload.splitlines():
            line = line.strip()
            if not line:
                continue
            obj = _json.loads(line)
            values = []
            for name in self.column_names:
                if name in obj:
                    v = obj[name]
                    values.append(Json(v) if isinstance(v, (dict, list)) else v)
                elif name in self.defaults:
                    values.append(self.defaults[name])
                else:
                    values.append(None)
            events.append(ParsedEvent(INSERT, tuple(values)))
        return events


class IdentityParser(Parser):
    """Whole payload → one `data` column (plaintext/binary,
    reference: IdentityParser data_format.rs:831)."""

    def __init__(self, binary: bool = False, split_lines: bool = False) -> None:
        super().__init__(["data"])
        self.binary = binary
        self.split_lines = split_lines

    def parse(self, payload: Any) -> list[ParsedEvent]:
        if self.split_lines:
            return [
                ParsedEvent(INSERT, (line,))
                for line in payload.splitlines()
                if line.strip()
            ]
        return [ParsedEvent(INSERT, (payload,))]


# -- readers ----------------------------------------------------------------


class Reader:
    """Produces (payload, source_id, metadata) tuples per poll."""

    #: True when a later payload with the same source_id REPLACES the earlier
    #: one (file re-read) — the driver then retracts the old rows first.
    replaces_sources = False

    def poll(self) -> tuple[list[tuple[Any, str, dict]], bool]:
        """Returns (entries, done)."""
        raise NotImplementedError


class FsReader(Reader):
    """File/directory/glob scanner with static and streaming modes
    (reference: posix_like.rs + scanner/filesystem.rs — streaming mode diffs
    the directory on each poll: new files insert, changed files replace,
    deleted files retract)."""

    replaces_sources = True

    def __init__(self, path: str | os.PathLike, mode: str = "static", binary: bool = False) -> None:
        self.path = os.fspath(path)
        self.mode = mode
        self.binary = binary
        self._seen: dict[str, tuple[float, int]] = {}  # path -> (mtime, size)
        self._done_static = False

    def _list_files(self) -> list[str]:
        if os.path.isdir(self.path):
            out = []
            for root, _dirs, files in os.walk(self.path):
                out.extend(os.path.join(root, f) for f in sorted(files))
            return sorted(out)
        matches = sorted(_glob.glob(self.path))
        return matches

    def _read_file(self, path: str) -> Any:
        if self.binary:
            with open(path, "rb") as f:
                return f.read()
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            return f.read()

    def poll(self) -> tuple[list[tuple[Any, str, dict]], bool]:
        if self.mode == "static":
            if self._done_static:
                return [], True
            self._done_static = True
            entries = []
            for path in self._list_files():
                try:
                    stat = os.stat(path)
                except FileNotFoundError:
                    continue
                sig = (stat.st_mtime, stat.st_size)
                if self._seen.get(path) == sig:
                    continue  # consumed before a resume; journal replays it
                self._seen[path] = sig
                entries.append(
                    (self._read_file(path), path, {"path": path, "deleted": False})
                )
            return entries, True
        # streaming: diff the directory
        entries = []
        current: dict[str, tuple[float, int]] = {}
        for path in self._list_files():
            try:
                stat = os.stat(path)
            except FileNotFoundError:
                continue
            current[path] = (stat.st_mtime, stat.st_size)
        for path, sig in current.items():
            if self._seen.get(path) != sig:
                entries.append(
                    (self._read_file(path), path, {"path": path, "deleted": False})
                )
        for path in set(self._seen) - set(current):
            entries.append((None, path, {"path": path, "deleted": True}))
        self._seen = current
        return entries, False

    # -- persistence (engine/persistence.py PersistentDriver) ---------------

    def state(self) -> dict:
        return {"seen": dict(self._seen), "done_static": self._done_static}

    def restore_state(self, state: dict) -> None:
        self._seen = dict(state.get("seen", {}))
        # a resumed static read re-scans once: already-consumed files are
        # skipped via _seen, files that appeared/changed while down are read
        self._done_static = False


class QueueReader(Reader):
    """Thread-fed queue (python ConnectorSubject, demo streams)."""

    def __init__(self) -> None:
        self.queue: "queue.Queue[Any]" = queue.Queue()
        self.closed = False

    def push(self, payload: Any, source_id: str = "q", metadata: dict | None = None) -> None:
        self.queue.put((payload, source_id, metadata or {}))

    def close(self) -> None:
        self.closed = True

    def poll(self) -> tuple[list[tuple[Any, str, dict]], bool]:
        entries = []
        while True:
            try:
                entries.append(self.queue.get_nowait())
            except queue.Empty:
                break
        return entries, self.closed and self.queue.empty()


# -- input driver -----------------------------------------------------------


class InputDriver:
    """Pumps one Reader+Parser into an InputSession; polled between commits
    (the analog of the reference's poller closure, connectors/mod.rs:720)."""

    def __init__(
        self,
        session: InputSession,
        reader: Reader,
        parser: Parser,
        *,
        primary_key_indices: Sequence[int] | None = None,
        source_name: str = "input",
        append_metadata: bool = False,
        autocommit_duration_ms: int | None = None,
    ) -> None:
        self.session = session
        self.reader = reader
        self.parser = parser
        self.pk = list(primary_key_indices) if primary_key_indices else None
        self.source_name = source_name
        #: max seconds this connector's rows may wait before a commit
        #: (the pump loop batches accordingly); 0 commits on every poll
        self.autocommit_s = (autocommit_duration_ms or 0) / 1000.0
        self.append_metadata = append_metadata
        self._per_source_rows: dict[str, list[tuple[Pointer, tuple]]] = {}
        self._seq = 0
        self.done = False
        # monitoring counters (internals/monitoring.py reads these)
        self.entries_total = 0
        self.batches_total = 0
        self.last_entry_wall: float | None = None
        #: wall stamp of the oldest row fed to the session and not yet
        #: committed; the runner pops it per commit to observe the
        #: ingest->sink latency histogram
        self.first_pending_wall: float | None = None
        self._m_entries = _metrics.REGISTRY.counter(
            "pathway_connector_entries_total",
            "entries ingested per connector",
            connector=self.source_name,
        )
        self._m_batches = _metrics.REGISTRY.counter(
            "pathway_connector_batches_total",
            "reader poll batches per connector",
            connector=self.source_name,
        )
        # synchronization group pacing (io/_synchronization.py): events
        # whose sync column runs ahead of the group wait here in order
        self.sync_group: Any = None
        self.sync_col: int | None = None
        # (kind, key, values, track, source_id) held back by the group;
        # deque: drains are O(1) per released event
        import collections as _collections

        self._sync_backlog: Any = _collections.deque()
        self._done_pending = False

    def effective_autocommit_s(self) -> float:
        """The autocommit window scaled by device-pipeline pressure: a
        congested device stage wants fewer, fatter commits, so the
        adaptive controller widens ingest windows (up to 4x) while
        commits are staged in flight. Host-only programs and the
        synchronous path (``PATHWAY_TPU_ASYNC_DEVICE=0``) always see the
        configured window unchanged; a 0-window connector (queries)
        stays immediate — scaling zero keeps retrieval overlapped with
        ingest instead of stalled behind it."""
        if self.autocommit_s <= 0.0:
            return self.autocommit_s
        from pathway_tpu.engine import device_pipeline

        return self.autocommit_s * device_pipeline.ingest_window_scale()

    def _key_for(self, values: tuple, source_id: str, index: int) -> Pointer:
        if self.pk is not None:
            return ref_scalar(*[values[i] for i in self.pk])
        self._seq += 1
        return hash_values(
            (self.source_name, source_id, index, self._seq), salt=b"connector"
        )

    def _feed(self, kind: str, key: Pointer, values: tuple | None, track: list | None) -> None:
        if kind == UPSERT:
            # upsert session: insert overlays, None deletes by key
            if values is None:
                self.session.remove(key)
            else:
                self.session.insert(key, values)
        elif kind == INSERT:
            self.session.insert(key, values)
            if track is not None:
                track.append((key, values))
        else:
            self.session.remove(key, values)

    def _sync_admit(self, values: tuple | None) -> bool:
        """Synchronization-group gate: once anything is backlogged, later
        events queue behind it to preserve order. Events without a usable
        sync time (None) are not paced."""
        if self.sync_group is None:
            return True
        if self._sync_backlog:
            return False
        if values is None or values[self.sync_col] is None:
            return True
        return self.sync_group.admit(self, values[self.sync_col])

    def _drain_backlog(self) -> bool:
        produced = False
        while self._sync_backlog:
            kind, key, values, track, _src = self._sync_backlog[0]
            t = values[self.sync_col] if values is not None else None
            if t is not None and not self.sync_group.admit(self, t):
                break
            self._sync_backlog.popleft()
            self._feed(kind, key, values, track)
            produced = True
        self._note_pending()
        return produced

    def _note_pending(self) -> None:
        if self.sync_group is None:
            return
        head_t = None
        if self._sync_backlog:
            head_values = self._sync_backlog[0][2]
            if head_values is not None:
                head_t = head_values[self.sync_col]
        self.sync_group.note_pending(self, head_t)

    def _poll_reader(self) -> tuple[list, bool]:
        """``reader.poll()`` with graceful degradation: transient I/O
        errors (``OSError`` — a network filesystem hiccup, a vanished NFS
        mount, a refused socket) get ``PATHWAY_TPU_CONNECTOR_RETRIES``
        bounded retries (default 3, 0 disables) with exponential backoff
        + jitter, counted in ``pathway_connector_retries_total``.  When
        retries exhaust, the original error re-raises: fail-stop stays
        the explicit fallback.  Non-I/O errors (parse bugs, type errors)
        never retry."""
        try:
            return self.reader.poll()
        except OSError:
            retries = int(
                os.environ.get("PATHWAY_TPU_CONNECTOR_RETRIES", "3")
            )
            if retries <= 0:
                raise
            import random as _random

            counter = _metrics.REGISTRY.counter(
                "pathway_connector_retries_total",
                "connector reader polls retried after transient I/O "
                "errors",
            )
            delay = 0.05
            for attempt in range(retries):
                counter.inc(1)
                _time.sleep(delay * (0.5 + _random.random()))
                delay = min(delay * 2, 2.0)
                try:
                    return self.reader.poll()
                except OSError:
                    if attempt == retries - 1:
                        raise
            raise  # unreachable; keeps the type checker honest

    def poll(self) -> str:
        if self.done:
            return "done"
        produced = False
        if self._sync_backlog:
            produced = self._drain_backlog()
        if self._done_pending:
            entries, done = [], True
        else:
            entries, done = self._poll_reader()
        if entries:
            self.entries_total += len(entries)
            self.batches_total += 1
            self.last_entry_wall = _time.monotonic()
            self._m_entries.inc(len(entries))
            self._m_batches.inc(1)
        replaces = self.reader.replaces_sources
        notify_source = getattr(self.session, "on_source", None)
        for payload, source_id, metadata in entries:
            if notify_source is not None:
                notify_source(source_id)
            # retract previously-emitted rows of a replaced/deleted source
            old_rows = self._per_source_rows.pop(source_id, None) if replaces else None
            if old_rows:
                for key, row in old_rows:
                    self.session.remove(key, row)
                produced = True
            if replaces and self._sync_backlog:
                # held-back events of the replaced source version must not
                # surface later: they were superseded before emission
                self._sync_backlog = type(self._sync_backlog)(
                    e for e in self._sync_backlog if e[4] != source_id
                )
            if metadata.get("deleted"):
                continue
            if hasattr(self.parser, "reset"):
                self.parser.reset()
            events = self.parser.parse(payload)
            new_rows: list[tuple[Pointer, tuple]] = []
            for i, event in enumerate(events):
                values = event.values
                if values is not None and self.append_metadata:
                    values = values + (Json(dict(metadata)),)
                if event.key is not None:
                    if len(event.key) == 1 and isinstance(event.key[0], Pointer):
                        key = event.key[0]  # loopback streams keep row ids
                    else:
                        key = ref_scalar(*event.key)
                elif values is not None:
                    key = self._key_for(values, source_id, i)
                else:
                    raise ValueError(
                        "connector event without values needs an explicit key"
                    )
                track = new_rows if (event.kind == INSERT and replaces) else None
                if self._sync_admit(values):
                    self._feed(event.kind, key, values, track)
                    produced = True
                else:
                    self._sync_backlog.append(
                        (event.kind, key, values, track, source_id)
                    )
            if replaces and events:
                # backlogged inserts append into this same list when released
                self._per_source_rows[source_id] = new_rows
        self._note_pending()
        if produced and self.first_pending_wall is None:
            self.first_pending_wall = _time.monotonic()
        if done:
            if self._sync_backlog:
                # the group still holds events back; report idle until the
                # other sources release them
                self._done_pending = True
                return "data" if produced else "idle"
            self.done = True
            if self.sync_group is not None:
                self.sync_group.mark_done(self)
            return "done"
        return "data" if produced else "idle"


class BatchScheduleDriver:
    """Feeds predefined batches, one per commit (debug.StreamGenerator)."""

    def __init__(self, session: InputSession, batches: list[list[tuple[str, Pointer, tuple]]]):
        self.session = session
        self.batches = list(batches)

    def poll(self) -> str:
        if not self.batches:
            return "done"
        batch = self.batches.pop(0)
        for kind, key, values in batch:
            if kind == INSERT:
                self.session.insert(key, values)
            else:
                self.session.remove(key, values)
        return "data" if batch or self.batches else "done"


# -- formatters / writers ---------------------------------------------------


class Formatter:
    def header(self, column_names: Sequence[str]) -> str | None:
        return None

    def format(
        self, key: Pointer, values: tuple, column_names: Sequence[str], time: int, diff: int
    ) -> str:
        raise NotImplementedError


def _plain(value: Any) -> Any:
    if isinstance(value, Json):
        return value.value
    if isinstance(value, Pointer):
        return repr(value)
    if isinstance(value, tuple):
        return [_plain(v) for v in value]
    return value


class JsonLinesFormatter(Formatter):
    """(reference: JsonLinesFormatter data_format.rs:1822 — row + diff + time)"""

    def format(self, key, values, column_names, time, diff):
        obj = {name: _plain(v) for name, v in zip(column_names, values)}
        obj["diff"] = diff
        obj["time"] = time
        return _json.dumps(obj, default=str)


class DsvFormatter(Formatter):
    """(reference: DsvFormatter data_format.rs:938 — row + time + diff cols)"""

    def __init__(self, delimiter: str = ",") -> None:
        self.delimiter = delimiter

    @staticmethod
    def _row(out: _io.StringIO) -> str:
        # keep the default \r\n lineterminator while writing: the csv
        # module only quotes fields containing the delimiter, quotechar or
        # lineterminator characters, so with lineterminator="" an embedded
        # newline would be emitted RAW and split the record. Strip the
        # terminator afterwards (FileWriter adds its own "\n").
        return out.getvalue().rstrip("\r\n")

    def header(self, column_names: Sequence[str]) -> str:
        out = _io.StringIO()
        _csv.writer(out, delimiter=self.delimiter).writerow(
            list(column_names) + ["time", "diff"]
        )
        return self._row(out)

    def format(self, key, values, column_names, time, diff):
        out = _io.StringIO()
        _csv.writer(out, delimiter=self.delimiter).writerow(
            [_plain(v) for v in values] + [time, diff]
        )
        return self._row(out)


#: every live FileWriter, registered at construction.  Sink attachment
#: returns no driver handle (subscribe_table wires callbacks directly),
#: so mesh recovery reaches file sinks through this registry to rewind
#: them past rolled-back commits.
import weakref as _weakref

FILE_WRITERS: "_weakref.WeakSet[FileWriter]" = _weakref.WeakSet()


class FileWriter:
    """Line-oriented file sink (reference: FileWriter data_storage.rs:630).

    Tracks the byte offset at each commit boundary (a bounded trail of
    recent commits) so a mesh-recovery rollback can truncate exactly the
    lines of un-happened commits — the recovered run re-emits them with
    identical timestamps, keeping outputs bit-identical to a fault-free
    run.

    The trail is also made *durable*: every commit atomically rewrites a
    ``<path>.pw-offsets`` sidecar (run id + header end + trail).  A
    process relaunched under the SAME ``PATHWAY_RUN_ID`` (supervised
    restart after a full-mesh crash, or a rescale relaunch) resumes the
    existing sink file instead of truncating it: the tail past the last
    recorded commit boundary is dropped (those lines belonged to commits
    that never became durable) and the restored trail lets the startup
    rollback rewind to the mesh's last common commit — exactly-once
    output across a cold restart.  A fresh run gets a fresh run id, so it
    never resumes a stale file."""

    #: commit-boundary offsets kept per writer (matches the snapshot
    #: ring depth with slack; older commits can no longer be rolled to)
    _OFFSET_TRAIL = 8

    def __init__(self, path: str | os.PathLike, formatter: Formatter, column_names: Sequence[str]):
        self.path = os.fspath(path)
        self.formatter = formatter
        self.column_names = list(column_names)
        self._offsets_path = self.path + ".pw-offsets"
        self._run_id = os.environ.get("PATHWAY_RUN_ID", "")
        resumed = self._try_resume()
        if not resumed:
            self._file = open(self.path, "w", encoding="utf-8")
            header = formatter.header(self.column_names)
            if header:
                self._file.write(header + "\n")
            self._header_end = self._file.tell()
            self._commit_offsets: dict[int, int] = {}
        FILE_WRITERS.add(self)

    def _try_resume(self) -> bool:
        """Reopen an existing sink file when the durable offset sidecar
        proves it belongs to THIS run (same ``PATHWAY_RUN_ID``)."""
        if not self._run_id or not os.path.exists(self.path):
            return False
        try:
            with open(self._offsets_path, "r", encoding="utf-8") as fh:
                meta = _json.load(fh)
        except (OSError, ValueError):
            return False
        if meta.get("run_id") != self._run_id:
            return False
        try:
            offsets = {
                int(t): int(o) for t, o in meta["offsets"].items()
            }
            header_end = int(meta["header_end"])
        except (KeyError, TypeError, ValueError):
            return False
        self._file = open(self.path, "r+", encoding="utf-8")
        self._header_end = header_end
        self._commit_offsets = offsets
        # drop any partially written tail: bytes past the newest durable
        # commit boundary belong to a commit that never became durable
        durable_end = max(offsets.values()) if offsets else header_end
        self._file.truncate(durable_end)
        self._file.seek(durable_end)
        return True

    def _persist_offsets(self) -> None:
        """Atomically rewrite the sidecar (tmp + replace) so a crash
        leaves either the old or the new trail, never a torn one."""
        if not self._run_id:
            return
        tmp = self._offsets_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                _json.dump(
                    {
                        "run_id": self._run_id,
                        "header_end": self._header_end,
                        "offsets": {
                            str(t): o
                            for t, o in self._commit_offsets.items()
                        },
                    },
                    fh,
                )
            os.replace(tmp, self._offsets_path)
        except OSError:
            pass

    def on_change(self, key: Pointer, values: tuple, time: int, diff: int) -> None:
        self._file.write(
            self.formatter.format(key, values, self.column_names, time, diff) + "\n"
        )

    def on_time_end(self, time: int) -> None:
        if not self._file.closed:
            self._file.flush()
            self._commit_offsets[time] = self._file.tell()
            while len(self._commit_offsets) > self._OFFSET_TRAIL:
                del self._commit_offsets[min(self._commit_offsets)]
            self._persist_offsets()

    def rewind_to(self, time: int) -> None:
        """Truncate everything written after commit ``time`` (``-1`` =
        back to the header).  No-op when nothing newer was written."""
        if self._file.closed:
            return
        if time < 0:
            offset = self._header_end
        elif time in self._commit_offsets:
            offset = self._commit_offsets[time]
        else:
            newer = [t for t in self._commit_offsets if t > time]
            if not newer:
                return  # nothing after `time` reached this sink
            raise ValueError(
                f"sink {self.path}: cannot rewind to commit {time} — "
                f"its boundary offset is no longer tracked (trail keeps "
                f"{self._OFFSET_TRAIL} commits)"
            )
        self._file.flush()
        self._file.truncate(offset)
        self._file.seek(offset)
        self._commit_offsets = {
            t: o for t, o in self._commit_offsets.items() if t <= time
        }
        self._persist_offsets()

    def on_end(self) -> None:
        if not self._file.closed:
            self._file.flush()
            self._file.close()
