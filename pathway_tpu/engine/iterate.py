"""Fixed-point iteration (pw.iterate).

Reference: `Graph::iterate` builds a nested timely scope with product
timestamps (src/engine/dataflow.rs:3912-3977, iterate subscopes). The
TPU-native engine replaces scope nesting with a *host-driven loop* (the
strategy flagged in SURVEY.md §7): on every outer commit the node reruns the
iteration body over the current input state until the iterated tables stop
changing (or the step limit hits), then emits the delta against its previous
output. Output streams are identical to the reference's; the inner loop is
recomputed per affected commit rather than incrementally nested — the right
trade for a scheduler whose heavy math lives on the device anyway.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

from pathway_tpu.engine.batch import DeltaBatch, apply_batch_to_state
from pathway_tpu.engine.graph import Node, Scope
from pathway_tpu.engine.value import Pointer


class IterateNode(Node):
    """Recompute-on-change host loop.

    ``compute(input_states) -> output_state`` runs the full fixed point;
    ``input_states`` are key->row dicts of the inputs, the return value is
    the final key->row dict of the designated output table.

    Under sharded execution this node is pinned to worker 0 and sees every
    batch, while the local input replicas' ``current`` only hold one key
    shard — so sharded scopes read OWN mirrors built from received batches
    (same pattern as RecomputeNode / the InputMirrors operators in
    engine/graph.py); single-worker scopes read inputs' ``current``.
    """

    STATE_ATTRS = ("_input_states",)

    def __init__(
        self,
        scope: Scope,
        inputs: Sequence[Node],
        arity: int,
        compute: Callable[[list[dict]], dict],
    ) -> None:
        super().__init__(scope, list(inputs), arity)
        self.compute = compute
        self._input_states: list[dict] = [{} for _ in self.inputs]

    def process(self, time: int) -> DeltaBatch:
        sharded = self.scope.sharded
        changed = False
        for port in range(len(self.inputs)):
            batch = self.take(port)
            if batch:
                changed = True
                if sharded:
                    apply_batch_to_state(self._input_states[port], batch)
        out = DeltaBatch()
        if not changed:
            return out
        try:
            new_state = self.compute(
                self._input_states
                if sharded
                else [inp.current for inp in self.inputs]
            )
        except Exception as e:  # noqa: BLE001
            self.report(None, f"iterate error: {e!r}")
            return out
        for key, row in self.current.items():
            if new_state.get(key) != row:
                out.append(key, row, -1)
        for key, row in new_state.items():
            if self.current.get(key) != row:
                out.append(key, row, 1)
        return out
