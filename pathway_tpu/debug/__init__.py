"""Debug / notebook helpers.

(reference: python/pathway/debug/__init__.py — table_from_markdown :431,
compute_and_print :207, table_from_pandas, table_from_rows).
"""

from __future__ import annotations

import re
from typing import Any, Iterable, Sequence

from pathway_tpu.engine.value import Pointer, ref_scalar, unsafe_make_pointer
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.runner import GraphRunner
from pathway_tpu.internals.table import Table


def _parse_value(text: str) -> Any:
    text = text.strip()
    if text in ("", "None"):
        return None
    if text == "True":
        return True
    if text == "False":
        return False
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        pass
    return text


def table_from_markdown(
    table_def: str,
    *,
    id_from: Sequence[str] | None = None,
    schema: schema_mod.SchemaMetaclass | None = None,
    **kwargs: Any,
) -> Table:
    """Build a static table from a markdown/whitespace definition:

    >>> t = pw.debug.table_from_markdown('''
    ...    | name  | age
    ...  1 | Alice | 10
    ...  2 | Bob   | 9
    ... ''')

    The optional first unnamed column gives explicit row ids.
    """
    lines = [ln for ln in table_def.strip().splitlines() if ln.strip() and not set(ln.strip()) <= {"-", "|", " ", "+"}]
    if not lines:
        raise ValueError("empty table definition")

    def split(line: str) -> list[str]:
        if "|" in line:
            parts = [p.strip() for p in line.split("|")]
        else:
            parts = re.split(r"\s+", line.strip())
        return parts

    header = split(lines[0])
    has_leading_id = header and header[0] == ""
    if has_leading_id:
        header = header[1:]
    col_names = [h for h in header if h]

    rows: list[tuple] = []
    keys: list[Pointer] = []
    for ln in lines[1:]:
        parts = split(ln)
        if has_leading_id:
            key_text, parts = parts[0], parts[1:]
            keys.append(ref_scalar(_parse_value(key_text)))
        parts = parts[: len(col_names)] + [""] * (len(col_names) - len(parts))
        rows.append(tuple(_parse_value(p) for p in parts[: len(col_names)]))

    if schema is None:
        dtypes: dict[str, dt.DType] = {}
        for i, name in enumerate(col_names):
            col_dtype: dt.DType | None = None
            saw_none = False
            for row in rows:
                v = row[i]
                if v is None:
                    saw_none = True
                    continue
                vd = dt.dtype_of_value(v)
                col_dtype = vd if col_dtype is None else dt.lca(col_dtype, vd)
            if col_dtype is None:
                col_dtype = dt.ANY
            elif saw_none:
                col_dtype = dt.Optional_(col_dtype)
            dtypes[name] = col_dtype
        schema = schema_mod.schema_from_dict(dtypes)
    else:
        schema_dtypes = schema.dtypes()
        rows = [
            tuple(
                dt.normalize_value(v, schema_dtypes[n])
                for v, n in zip(row, col_names)
            )
            for row in rows
        ]

    return Table.from_rows(
        rows, schema, keys=keys if has_leading_id else None
    )


# reference alias
T = table_from_markdown


def table_from_rows(
    schema: schema_mod.SchemaMetaclass,
    rows: Iterable[tuple],
    **kwargs: Any,
) -> Table:
    return Table.from_rows(list(rows), schema)


def table_from_pandas(df: Any, *, id_from: Sequence[str] | None = None, **kwargs: Any) -> Table:
    import pandas as pd  # local import; pandas ships with the image

    col_names = list(df.columns)
    dtypes: dict[str, dt.DType] = {}
    for name in col_names:
        kind = df[name].dtype.kind
        if kind in "iu":
            dtypes[name] = dt.INT
        elif kind == "f":
            dtypes[name] = dt.FLOAT
        elif kind == "b":
            dtypes[name] = dt.BOOL
        else:
            dtypes[name] = dt.ANY
    schema = schema_mod.schema_from_dict(dtypes)
    rows = [tuple(df[c].iloc[i] for c in col_names) for i in range(len(df))]
    keys = None
    if id_from is not None:
        keys = [
            ref_scalar(*[df[c].iloc[i] for c in id_from]) for i in range(len(df))
        ]
    else:
        keys = [unsafe_make_pointer(int(k)) if isinstance(k, (int,)) else ref_scalar(k) for k in df.index]
    return Table.from_rows(rows, schema, keys=keys)


def table_to_dicts(table: Table) -> tuple[dict[Pointer, dict[str, Any]], list[str]]:
    runner = GraphRunner()
    (snapshot,) = runner.capture(table)
    names = table.column_names()
    return (
        {key: dict(zip(names, row)) for key, row in snapshot.items()},
        names,
    )


def table_to_pandas(table: Table) -> Any:
    import pandas as pd

    data, names = table_to_dicts(table)
    index = list(data.keys())
    return pd.DataFrame(
        {n: [data[k][n] for k in index] for n in names}, index=index
    )


def compute_and_print(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: int | None = None,
    **kwargs: Any,
) -> None:
    data, names = table_to_dicts(table)
    header = (["id"] if include_id else []) + names
    rows = []
    for key in sorted(data.keys(), key=int):
        row = data[key]
        cells = ([repr(key)] if include_id else []) + [
            repr(row[n]) for n in names
        ]
        rows.append(cells)
    if n_rows is not None:
        rows = rows[:n_rows]
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(header)
    ]
    print(" | ".join(h.ljust(w) for h, w in zip(header, widths)))
    for cells in rows:
        print(" | ".join(c.ljust(w) for c, w in zip(cells, widths)))


class StreamGenerator:
    """Emit explicit batches at artificial times for streaming tests
    (reference: debug/__init__.py:500)."""

    def __init__(self) -> None:
        self._counter = 0

    def table_from_list_of_batches_by_workers(
        self,
        batches: Sequence[dict[int, list[dict[str, Any]]]],
        schema: schema_mod.SchemaMetaclass,
    ) -> Table:
        flat = [[row for rows in batch.values() for row in rows] for batch in batches]
        return self.table_from_list_of_batches(flat, schema)

    def table_from_list_of_batches(
        self,
        batches: Sequence[list[dict[str, Any]]],
        schema: schema_mod.SchemaMetaclass,
    ) -> Table:
        from pathway_tpu.engine.connectors import INSERT, BatchScheduleDriver
        from pathway_tpu.engine.graph import Scope
        from pathway_tpu.internals.table import TableSpec

        names = schema.column_names()
        dtypes = schema.dtypes()
        seq = iter(range(10**9))
        schedule = []
        for batch in batches:
            entries = []
            for row in batch:
                values = tuple(
                    dt.normalize_value(row.get(n), dtypes[n]) for n in names
                )
                entries.append((INSERT, ref_scalar("sg", next(seq)), values))
            schedule.append(entries)

        def attach(scope: Scope, make_driver: bool = True):
            session = scope.input_session(len(names))
            if not make_driver:
                return session, None
            driver = BatchScheduleDriver(session, schedule)
            return session, driver

        return Table(
            TableSpec("input", [], {"attach": attach}),
            names,
            dtypes,
            name="stream-generator",
        )


def compute_and_print_update_stream(table: Table, **kwargs: Any) -> None:
    runner = GraphRunner()
    node = runner.build(table)
    updates: list[tuple] = []
    runner.scope.subscribe_table(
        node,
        on_change=lambda key, row, time, diff: updates.append((key, row, time, diff)),
    )
    runner.run_static()
    names = table.column_names()
    header = ["id", *names, "__time__", "__diff__"]
    print(" | ".join(header))
    for key, row, time, diff in updates:
        print(" | ".join([repr(key), *[repr(v) for v in row], str(time), str(diff)]))
