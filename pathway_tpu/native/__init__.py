"""Native (C++) engine-core kernels with transparent build + fallback.

The reference's hot loop is native (Rust, src/engine/dataflow.rs); this
package provides the equivalent native floor for the TPU build's host
control plane: CPython C++ kernels for per-row object plumbing
(enginecore.cpp), compiled on first import with g++ and cached next to the
source. Everything degrades gracefully to the pure-Python implementations
when no toolchain is available — behavior is identical, only slower.

A failed build or import is NOT silent: the first failure logs one
structured warning (module path + exception) on the
``pathway_tpu.native`` logger, and the reason stays queryable via
:func:`load_error` — a several-fold slowdown should never have to be
bisected back to a missing compiler.

``PATHWAY_TPU_NATIVE_SO`` overrides the shared-object path entirely
(tools/check.py points it at an ASan/UBSan-instrumented build so the
parity suite exercises the sanitized kernels).

Public surface:
- ``available()`` — True when the compiled kernels are loaded.
- ``kernels`` — the extension module or None.
- ``load_error()`` — why the native module is absent (None when loaded).
"""

from __future__ import annotations

import importlib.util
import logging
import os
import subprocess
import sys
import sysconfig

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "enginecore.cpp")

kernels = None

#: why the native module is absent (None when loaded); see load_error()
_load_error: str | None = None
_warned = False


def load_error() -> str | None:
    """The reason the native extension is unavailable: a build/import
    failure description, the disable-flag notice, or None when loaded."""
    return _load_error


def _note_failure(message: str, *, warn: bool = True) -> None:
    global _load_error, _warned
    _load_error = message
    if warn and not _warned:
        _warned = True
        logging.getLogger("pathway_tpu.native").warning(
            "native kernels unavailable, falling back to pure-Python "
            "implementations (identical results, slower): %s",
            message,
        )


def _so_path() -> str:
    tag = f"cpython-{sys.version_info.major}{sys.version_info.minor}"
    return os.path.join(_DIR, f"_enginecore.{tag}.so")


def _build() -> str | None:
    so = _so_path()
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(_SRC):
        return so
    include = sysconfig.get_path("include")
    import numpy as np

    cmd = [
        "g++",
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        f"-I{include}",
        f"-I{np.get_include()}",
        _SRC,
        "-o",
        so + ".tmp",
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, text=True, timeout=120
        )
        os.replace(so + ".tmp", so)
        return so
    except (subprocess.SubprocessError, OSError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        _note_failure(
            f"compiling {_SRC} failed: {type(e).__name__}: "
            f"{detail.strip()[:500]}"
        )
        return None


def _load():
    global kernels
    so = os.environ.get("PATHWAY_TPU_NATIVE_SO")
    if so:
        if not os.path.exists(so):
            _note_failure(f"PATHWAY_TPU_NATIVE_SO={so} does not exist")
            return
    else:
        so = _build()
        if so is None:
            return
    try:
        spec = importlib.util.spec_from_file_location("_enginecore", so)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        kernels = mod
    except Exception as e:  # noqa: BLE001 — any load failure -> pure Python
        kernels = None
        _note_failure(f"importing {so} failed: {type(e).__name__}: {e}")


if os.environ.get("PATHWAY_TPU_DISABLE_NATIVE") != "1":
    _load()
else:
    # explicit opt-out is not a failure: record why, but don't warn
    _note_failure("disabled via PATHWAY_TPU_DISABLE_NATIVE=1", warn=False)


def available() -> bool:
    return kernels is not None


def hit_counts() -> dict[str, int]:
    """Per-kernel invocation counters since process start (or the last
    :func:`reset_hit_counts`); empty when the native module is absent.
    bench_dataflow records this next to EXCHANGE_STATS so a silent import
    regression shows up in the bench JSON, not just as a slowdown."""
    if kernels is None or not hasattr(kernels, "hit_counts"):
        return {}
    return kernels.hit_counts()


def kernel_ns() -> dict[str, int]:
    """Cumulative wall nanoseconds spent inside each native kernel since
    process start (or the last :func:`reset_hit_counts`); empty when the
    native module is absent or the .so predates the timers."""
    if kernels is None or not hasattr(kernels, "kernel_ns"):
        return {}
    return kernels.kernel_ns()


def reset_hit_counts() -> None:
    if kernels is not None and hasattr(kernels, "reset_hit_counts"):
        kernels.reset_hit_counts()
