/* Native engine-core kernels for the hot per-row paths.
 *
 * The reference implements its entire dataflow hot loop natively (Rust,
 * src/engine/dataflow.rs); here the control plane stays in Python but the
 * per-row floors — entry construction, consolidation, state-map
 * application, filter sweeps — run as CPython C++ kernels over the same
 * object representation (list of (key, row, diff) tuples). Columnar math
 * lives in engine/device.py (NumPy/XLA); these kernels cover the object
 * plumbing numpy cannot.
 *
 * Built on demand by pathway_tpu/native/__init__.py (g++ -O3); the engine
 * transparently falls back to the pure-Python implementations when the
 * toolchain or the .so is unavailable.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>

namespace {

/* -- columnar extraction -----------------------------------------------------
 *
 * One C pass replaces the Python `[row[c] for row in rows]` + np.asarray
 * dance of engine/device.py::_extract. Exact-type discipline matches the
 * Python path: only genuine int/float/bool cells columnarise (subclasses —
 * Pointer(int) keys, np scalars — and str/None/ERROR fall back), so the
 * row interpreter keeps ownership of every edge case.
 */

enum ColKind { K_UNSET = 0, K_INT, K_FLOAT, K_BOOL, K_FAIL };

/* Extract rows[i][col] (item_idx < 0) or entries[i][1][col] into a fresh
 * typed ndarray; NULL+no-error means "not cleanly columnar". */
PyObject *extract_col_core(PyObject *seq, Py_ssize_t col, int from_entries) {
  Py_ssize_t n = PyList_GET_SIZE(seq);
  if (n == 0) return nullptr; /* empty: Python path decides */
  ColKind kind = K_UNSET;
  /* first pass: decide the dtype from the first cell, verify the rest */
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PyList_GET_ITEM(seq, i);
    if (from_entries && (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 3))
      return nullptr;
    PyObject *row = from_entries ? PyTuple_GET_ITEM(item, 1) : item;
    if (!PyTuple_Check(row) || PyTuple_GET_SIZE(row) <= col) return nullptr;
    PyObject *v = PyTuple_GET_ITEM(row, col);
    PyTypeObject *t = Py_TYPE(v);
    ColKind k = t == &PyLong_Type    ? K_INT
                : t == &PyFloat_Type ? K_FLOAT
                : t == &PyBool_Type  ? K_BOOL
                                     : K_FAIL;
    if (k == K_FAIL) return nullptr;
    if (kind == K_UNSET)
      kind = k;
    else if (kind != k)
      return nullptr; /* mixed dtypes: exact semantics live row-wise */
  }
  npy_intp dims[1] = {n};
  int typenum = kind == K_INT ? NPY_INT64 : kind == K_FLOAT ? NPY_FLOAT64 : NPY_BOOL;
  PyObject *arr = PyArray_SimpleNew(1, dims, typenum);
  if (!arr) return nullptr; /* with error set */
  char *data = PyArray_BYTES((PyArrayObject *)arr);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PyList_GET_ITEM(seq, i);
    PyObject *row = from_entries ? PyTuple_GET_ITEM(item, 1) : item;
    PyObject *v = PyTuple_GET_ITEM(row, col);
    if (kind == K_INT) {
      int overflow = 0;
      long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
      if (overflow || (x == -1 && PyErr_Occurred())) {
        PyErr_Clear();
        Py_DECREF(arr);
        return nullptr; /* bigint: row path computes exact Python ints */
      }
      ((npy_int64 *)data)[i] = (npy_int64)x;
    } else if (kind == K_FLOAT) {
      ((npy_double *)data)[i] = PyFloat_AS_DOUBLE(v);
    } else {
      ((npy_bool *)data)[i] = (v == Py_True);
    }
  }
  return arr;
}

/* extract_column(seq, col, from_entries) -> ndarray | None
 * seq is a list of row tuples (from_entries=0) or (key,row,diff) entries
 * (from_entries=1). */
PyObject *extract_column(PyObject *, PyObject *args) {
  PyObject *rows;
  Py_ssize_t col;
  int from_entries;
  if (!PyArg_ParseTuple(args, "O!np", &PyList_Type, &rows, &col, &from_entries))
    return nullptr;
  PyObject *arr = extract_col_core(rows, col, from_entries);
  if (!arr) {
    if (PyErr_Occurred()) return nullptr;
    Py_RETURN_NONE;
  }
  return arr;
}

/* entry_diffs(entries) -> int64 ndarray of each entry's diff. */
PyObject *entry_diffs(PyObject *, PyObject *args) {
  PyObject *entries;
  if (!PyArg_ParseTuple(args, "O!", &PyList_Type, &entries)) return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(entries);
  npy_intp dims[1] = {n};
  PyObject *diffs = PyArray_SimpleNew(1, dims, NPY_INT64);
  if (!diffs) return nullptr;
  npy_int64 *ddata = (npy_int64 *)PyArray_BYTES((PyArrayObject *)diffs);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    if (!PyTuple_Check(e) || PyTuple_GET_SIZE(e) != 3) {
      PyErr_SetString(PyExc_ValueError, "malformed entry");
      Py_DECREF(diffs);
      return nullptr;
    }
    long long d = PyLong_AsLongLong(PyTuple_GET_ITEM(e, 2));
    if (d == -1 && PyErr_Occurred()) {
      Py_DECREF(diffs);
      return nullptr;
    }
    ddata[i] = (npy_int64)d;
  }
  return diffs;
}

/* consolidate(entries) -> (new_entries | None, insert_only)
 *
 * None as first element means "already consolidated as-is" (the cheap
 * precheck passed); insert_only reports unique-key all-positive shape. */
PyObject *consolidate(PyObject *, PyObject *args) {
  PyObject *entries;
  if (!PyArg_ParseTuple(args, "O!", &PyList_Type, &entries)) return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(entries);

  /* Precheck: all diffs > 0 and keys unique. */
  PyObject *seen = PySet_New(nullptr);
  if (!seen) return nullptr;
  bool clean = true;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    if (!PyTuple_Check(e) || PyTuple_GET_SIZE(e) != 3) {
      clean = false;
      break;
    }
    PyObject *key = PyTuple_GET_ITEM(e, 0);
    PyObject *diff = PyTuple_GET_ITEM(e, 2);
    long d = PyLong_AsLong(diff);
    if (d == -1 && PyErr_Occurred()) {
      Py_DECREF(seen);
      return nullptr;
    }
    if (d <= 0) {
      clean = false;
      break;
    }
    int contains = PySet_Contains(seen, key);
    if (contains < 0) {
      Py_DECREF(seen);
      return nullptr;
    }
    if (contains) {
      clean = false;
      break;
    }
    if (PySet_Add(seen, key) < 0) {
      Py_DECREF(seen);
      return nullptr;
    }
  }
  Py_DECREF(seen);
  if (clean) {
    return Py_BuildValue("(OO)", Py_None, Py_True);
  }

  /* Full path: merge duplicate (key, row) entries preserving first-seen
   * order, drop zero diffs. acc maps (key, row) -> [row, diff] — the dict
   * resolves hash collisions by row equality; unhashable rows fall back to
   * identity. */
  PyObject *acc = PyDict_New();
  PyObject *order = PyList_New(0);
  if (!acc || !order) {
    Py_XDECREF(acc);
    Py_XDECREF(order);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    PyObject *key = PyTuple_GET_ITEM(e, 0);
    PyObject *row = PyTuple_GET_ITEM(e, 1);
    PyObject *diff = PyTuple_GET_ITEM(e, 2);
    Py_hash_t rh = PyObject_Hash(row);
    PyObject *slot;
    if (rh == -1) {
      PyErr_Clear(); /* unhashable row: fall back to identity */
      slot = Py_BuildValue("(On)", key, (Py_ssize_t)(uintptr_t)row);
    } else {
      slot = PyTuple_Pack(2, key, row);
    }
    if (!slot) goto fail;
    {
      PyObject *found = PyDict_GetItemWithError(acc, slot);
      if (!found && PyErr_Occurred()) {
        Py_DECREF(slot);
        goto fail;
      }
      if (!found) {
        PyObject *pair = PyList_New(2);
        if (!pair) {
          Py_DECREF(slot);
          goto fail;
        }
        Py_INCREF(row);
        PyList_SET_ITEM(pair, 0, row);
        Py_INCREF(diff);
        PyList_SET_ITEM(pair, 1, diff);
        if (PyDict_SetItem(acc, slot, pair) < 0) {
          Py_DECREF(pair);
          Py_DECREF(slot);
          goto fail;
        }
        Py_DECREF(pair);
        if (PyList_Append(order, slot) < 0) {
          Py_DECREF(slot);
          goto fail;
        }
      } else {
        PyObject *old = PyList_GET_ITEM(found, 1);
        PyObject *sum = PyNumber_Add(old, diff);
        if (!sum) {
          Py_DECREF(slot);
          goto fail;
        }
        PyList_SetItem(found, 1, sum); /* steals sum */
      }
      Py_DECREF(slot);
    }
  }
  {
    PyObject *out = PyList_New(0);
    if (!out) goto fail;
    Py_ssize_t m = PyList_GET_SIZE(order);
    for (Py_ssize_t i = 0; i < m; i++) {
      PyObject *slot = PyList_GET_ITEM(order, i);
      PyObject *pair = PyDict_GetItemWithError(acc, slot);
      if (!pair) {
        Py_DECREF(out);
        goto fail;
      }
      PyObject *row = PyList_GET_ITEM(pair, 0);
      PyObject *diff = PyList_GET_ITEM(pair, 1);
      long d = PyLong_AsLong(diff);
      if (d == -1 && PyErr_Occurred()) {
        Py_DECREF(out);
        goto fail;
      }
      if (d != 0) {
        PyObject *entry =
            PyTuple_Pack(3, PyTuple_GET_ITEM(slot, 0), row, diff);
        if (!entry || PyList_Append(out, entry) < 0) {
          Py_XDECREF(entry);
          Py_DECREF(out);
          goto fail;
        }
        Py_DECREF(entry);
      }
    }
    Py_DECREF(acc);
    Py_DECREF(order);
    PyObject *res = Py_BuildValue("(NO)", out, Py_False);
    return res;
  }
fail:
  Py_DECREF(acc);
  Py_DECREF(order);
  return nullptr;
}

/* apply_state(state_dict, entries, insert_only) -> None
 * Mirrors batch.apply_batch_to_state. */
PyObject *apply_state(PyObject *, PyObject *args) {
  PyObject *state, *entries;
  int insert_only;
  if (!PyArg_ParseTuple(args, "O!O!p", &PyDict_Type, &state, &PyList_Type,
                        &entries, &insert_only))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(entries);
  if (insert_only) {
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject *e = PyList_GET_ITEM(entries, i);
      if (PyDict_SetItem(state, PyTuple_GET_ITEM(e, 0),
                         PyTuple_GET_ITEM(e, 1)) < 0)
        return nullptr;
    }
    Py_RETURN_NONE;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    long d = PyLong_AsLong(PyTuple_GET_ITEM(e, 2));
    if (d == -1 && PyErr_Occurred()) return nullptr;
    if (d < 0) {
      if (PyDict_DelItem(state, PyTuple_GET_ITEM(e, 0)) < 0) PyErr_Clear();
    }
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    long d = PyLong_AsLong(PyTuple_GET_ITEM(e, 2));
    if (d == -1 && PyErr_Occurred()) return nullptr;
    if (d > 0) {
      if (PyDict_SetItem(state, PyTuple_GET_ITEM(e, 0),
                         PyTuple_GET_ITEM(e, 1)) < 0)
        return nullptr;
    }
  }
  Py_RETURN_NONE;
}

/* build_entries(entries, columns) -> list
 * New entries with rows rebuilt from per-column Python lists (the tail of
 * the columnar expression path): row_i = (columns[0][i], columns[1][i],…),
 * keys/diffs reused from the input entries. */
PyObject *build_entries(PyObject *, PyObject *args) {
  PyObject *entries, *columns;
  if (!PyArg_ParseTuple(args, "O!O!", &PyList_Type, &entries, &PyList_Type,
                        &columns))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(entries);
  Py_ssize_t ncols = PyList_GET_SIZE(columns);
  for (Py_ssize_t c = 0; c < ncols; c++) {
    PyObject *col = PyList_GET_ITEM(columns, c);
    if (!PyList_Check(col) || PyList_GET_SIZE(col) != n) {
      PyErr_SetString(PyExc_ValueError, "column length mismatch");
      return nullptr;
    }
  }
  PyObject *out = PyList_New(n);
  if (!out) return nullptr;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    PyObject *row = PyTuple_New(ncols);
    if (!row) {
      Py_DECREF(out);
      return nullptr;
    }
    for (Py_ssize_t c = 0; c < ncols; c++) {
      PyObject *v = PyList_GET_ITEM(PyList_GET_ITEM(columns, c), i);
      Py_INCREF(v);
      PyTuple_SET_ITEM(row, c, v);
    }
    PyObject *entry =
        PyTuple_Pack(3, PyTuple_GET_ITEM(e, 0), row, PyTuple_GET_ITEM(e, 2));
    Py_DECREF(row);
    if (!entry) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, entry);
  }
  return out;
}

/* filter_truthy(entries, col) -> (list | None)
 * Keep entries whose row[col] is truthy. Returns None (for Python-side
 * fallback) if any condition value is not a plain bool — error poisoning
 * and odd truthiness keep their row-wise semantics. */
PyObject *filter_truthy(PyObject *, PyObject *args) {
  PyObject *entries;
  Py_ssize_t col;
  if (!PyArg_ParseTuple(args, "O!n", &PyList_Type, &entries, &col))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(entries);
  PyObject *out = PyList_New(0);
  if (!out) return nullptr;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    PyObject *row = PyTuple_GET_ITEM(e, 1);
    if (!PyTuple_Check(row) || PyTuple_GET_SIZE(row) <= col) {
      Py_DECREF(out);
      Py_RETURN_NONE;
    }
    PyObject *v = PyTuple_GET_ITEM(row, col);
    if (v == Py_True) {
      if (PyList_Append(out, e) < 0) {
        Py_DECREF(out);
        return nullptr;
      }
    } else if (v != Py_False) {
      Py_DECREF(out);
      Py_RETURN_NONE; /* non-bool condition: row-wise semantics */
    }
  }
  return out;
}

PyMethodDef methods[] = {
    {"consolidate", consolidate, METH_VARARGS,
     "consolidate(entries) -> (entries|None, insert_only)"},
    {"apply_state", apply_state, METH_VARARGS,
     "apply_state(state, entries, insert_only)"},
    {"build_entries", build_entries, METH_VARARGS,
     "build_entries(entries, columns) -> entries"},
    {"filter_truthy", filter_truthy, METH_VARARGS,
     "filter_truthy(entries, col) -> entries|None"},
    {"extract_column", extract_column, METH_VARARGS,
     "extract_column(seq, col, from_entries) -> ndarray|None"},
    {"entry_diffs", entry_diffs, METH_VARARGS,
     "entry_diffs(entries) -> int64 ndarray"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT,
                         "_enginecore",
                         "Native engine-core kernels",
                         -1,
                         methods,
                         nullptr,
                         nullptr,
                         nullptr,
                         nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__enginecore(void) {
  import_array();
  return PyModule_Create(&moduledef);
}
