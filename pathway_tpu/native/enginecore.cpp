/* Native engine-core kernels for the hot per-row paths.
 *
 * The reference implements its entire dataflow hot loop natively (Rust,
 * src/engine/dataflow.rs); here the control plane stays in Python but the
 * per-row floors — entry construction, consolidation, state-map
 * application, filter sweeps — run as CPython C++ kernels over the same
 * object representation (list of (key, row, diff) tuples). Columnar math
 * lives in engine/device.py (NumPy/XLA); these kernels cover the object
 * plumbing numpy cannot.
 *
 * Built on demand by pathway_tpu/native/__init__.py (g++ -O3); the engine
 * transparently falls back to the pure-Python implementations when the
 * toolchain or the .so is unavailable.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>

#include <cmath>
#include <ctime>
#include <vector>

namespace {

/* -- per-kernel hit counters -------------------------------------------------
 *
 * Cheap engagement probes: every kernel entry bumps its slot, and
 * ``hit_counts()`` exposes the table as a dict. bench_dataflow records it
 * next to EXCHANGE_STATS so a silent import regression (everything
 * falling back to Python loops) is visible in the bench JSON, and the
 * smoke test asserts the counters actually move on a groupby+join run.
 */

enum HitKernel {
  H_EXTRACT_COLUMN = 0,
  H_ENTRY_DIFFS,
  H_CONSOLIDATE,
  H_APPLY_STATE,
  H_BUILD_ENTRIES,
  H_FILTER_TRUTHY,
  H_JOIN_INSERT_INNER,
  H_POINTERS_TO_BYTES,
  H_BYTES_TO_POINTERS,
  H_ENTRY_KEYS_BYTES,
  H_HASH_JOIN_PAIRS,
  H_COLUMNS_TO_ENTRIES,
  H_HASH_TUPLES_BATCH,
  H_SHARD_VALUES,
  H_ENTRIES_TO_SIDE,
  H_MATCH_PAIRS_I64,
  H_SESSION_OVERLAY,
  H_N_KERNELS,
};

const char *const HIT_NAMES[H_N_KERNELS] = {
    "extract_column",   "entry_diffs",      "consolidate",
    "apply_state",      "build_entries",    "filter_truthy",
    "join_insert_inner", "pointers_to_bytes", "bytes_to_pointers",
    "entry_keys_bytes", "hash_join_pairs",  "columns_to_entries",
    "hash_tuples_batch", "shard_values",    "entries_to_side",
    "match_pairs_i64",  "session_overlay",
};

unsigned long long g_hits[H_N_KERNELS] = {0};

/* cumulative wall nanoseconds inside each kernel (scope of the HIT
 * declaration to scope exit), feeding kernel_ns() and from there the
 * pathway_native_kernel_ns_total registry series */
unsigned long long g_ns[H_N_KERNELS] = {0};

struct KTimer {
  int id;
  struct timespec t0;
  explicit KTimer(int id_) : id(id_) {
    g_hits[id_]++;
    clock_gettime(CLOCK_MONOTONIC, &t0);
  }
  ~KTimer() {
    struct timespec t1;
    clock_gettime(CLOCK_MONOTONIC, &t1);
    long long d = (long long)(t1.tv_sec - t0.tv_sec) * 1000000000LL +
                  (long long)(t1.tv_nsec - t0.tv_nsec);
    if (d > 0) g_ns[id] += (unsigned long long)d;
  }
};

/* HIT(id) declares an RAII probe: bumps the hit counter on entry and
 * accumulates nanoseconds until the enclosing scope exits. Every call
 * site is a standalone statement, so the declaration is safe; __LINE__
 * keeps names unique within one scope. */
#define PW_HIT_CAT2(a, b) a##b
#define PW_HIT_CAT(a, b) PW_HIT_CAT2(a, b)
#define HIT(id) KTimer PW_HIT_CAT(_pw_ktimer_, __LINE__)(id)

PyObject *counts_dict(const unsigned long long table[H_N_KERNELS]) {
  PyObject *out = PyDict_New();
  if (!out) return nullptr;
  for (int i = 0; i < H_N_KERNELS; i++) {
    PyObject *v = PyLong_FromUnsignedLongLong(table[i]);
    if (!v || PyDict_SetItemString(out, HIT_NAMES[i], v) < 0) {
      Py_XDECREF(v);
      Py_DECREF(out);
      return nullptr;
    }
    Py_DECREF(v);
  }
  return out;
}

PyObject *hit_counts(PyObject *, PyObject *) { return counts_dict(g_hits); }

PyObject *kernel_ns(PyObject *, PyObject *) { return counts_dict(g_ns); }

PyObject *reset_hit_counts(PyObject *, PyObject *) {
  for (int i = 0; i < H_N_KERNELS; i++) {
    g_hits[i] = 0;
    g_ns[i] = 0;
  }
  Py_RETURN_NONE;
}

/* -- columnar extraction -----------------------------------------------------
 *
 * One C pass replaces the Python `[row[c] for row in rows]` + np.asarray
 * dance of engine/device.py::_extract. Exact-type discipline matches the
 * Python path: only genuine int/float/bool cells columnarise (subclasses —
 * Pointer(int) keys, np scalars — and str/None/ERROR fall back), so the
 * row interpreter keeps ownership of every edge case.
 */

enum ColKind { K_UNSET = 0, K_INT, K_FLOAT, K_BOOL, K_FAIL };

/* Extract rows[i][col] (item_idx < 0) or entries[i][1][col] into a fresh
 * typed ndarray; NULL+no-error means "not cleanly columnar". */
PyObject *extract_col_core(PyObject *seq, Py_ssize_t col, int from_entries) {
  Py_ssize_t n = PyList_GET_SIZE(seq);
  if (n == 0) return nullptr; /* empty: Python path decides */
  ColKind kind = K_UNSET;
  /* first pass: decide the dtype from the first cell, verify the rest */
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PyList_GET_ITEM(seq, i);
    if (from_entries && (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 3))
      return nullptr;
    PyObject *row = from_entries ? PyTuple_GET_ITEM(item, 1) : item;
    if (!PyTuple_Check(row) || PyTuple_GET_SIZE(row) <= col) return nullptr;
    PyObject *v = PyTuple_GET_ITEM(row, col);
    PyTypeObject *t = Py_TYPE(v);
    ColKind k = t == &PyLong_Type    ? K_INT
                : t == &PyFloat_Type ? K_FLOAT
                : t == &PyBool_Type  ? K_BOOL
                                     : K_FAIL;
    if (k == K_FAIL) return nullptr;
    if (kind == K_UNSET)
      kind = k;
    else if (kind != k)
      return nullptr; /* mixed dtypes: exact semantics live row-wise */
  }
  npy_intp dims[1] = {n};
  int typenum = kind == K_INT ? NPY_INT64 : kind == K_FLOAT ? NPY_FLOAT64 : NPY_BOOL;
  PyObject *arr = PyArray_SimpleNew(1, dims, typenum);
  if (!arr) return nullptr; /* with error set */
  char *data = PyArray_BYTES((PyArrayObject *)arr);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PyList_GET_ITEM(seq, i);
    PyObject *row = from_entries ? PyTuple_GET_ITEM(item, 1) : item;
    PyObject *v = PyTuple_GET_ITEM(row, col);
    if (kind == K_INT) {
      int overflow = 0;
      long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
      if (overflow || (x == -1 && PyErr_Occurred())) {
        PyErr_Clear();
        Py_DECREF(arr);
        return nullptr; /* bigint: row path computes exact Python ints */
      }
      ((npy_int64 *)data)[i] = (npy_int64)x;
    } else if (kind == K_FLOAT) {
      ((npy_double *)data)[i] = PyFloat_AS_DOUBLE(v);
    } else {
      ((npy_bool *)data)[i] = (v == Py_True);
    }
  }
  return arr;
}

/* extract_column(seq, col, from_entries) -> ndarray | None
 * seq is a list of row tuples (from_entries=0) or (key,row,diff) entries
 * (from_entries=1). */
PyObject *extract_column(PyObject *, PyObject *args) {
  HIT(H_EXTRACT_COLUMN);
  PyObject *rows;
  Py_ssize_t col;
  int from_entries;
  if (!PyArg_ParseTuple(args, "O!np", &PyList_Type, &rows, &col, &from_entries))
    return nullptr;
  PyObject *arr = extract_col_core(rows, col, from_entries);
  if (!arr) {
    if (PyErr_Occurred()) return nullptr;
    Py_RETURN_NONE;
  }
  return arr;
}

/* entry_diffs(entries) -> int64 ndarray of each entry's diff. */
PyObject *entry_diffs(PyObject *, PyObject *args) {
  HIT(H_ENTRY_DIFFS);
  PyObject *entries;
  if (!PyArg_ParseTuple(args, "O!", &PyList_Type, &entries)) return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(entries);
  npy_intp dims[1] = {n};
  PyObject *diffs = PyArray_SimpleNew(1, dims, NPY_INT64);
  if (!diffs) return nullptr;
  npy_int64 *ddata = (npy_int64 *)PyArray_BYTES((PyArrayObject *)diffs);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    if (!PyTuple_Check(e) || PyTuple_GET_SIZE(e) != 3) {
      PyErr_SetString(PyExc_ValueError, "malformed entry");
      Py_DECREF(diffs);
      return nullptr;
    }
    long long d = PyLong_AsLongLong(PyTuple_GET_ITEM(e, 2));
    if (d == -1 && PyErr_Occurred()) {
      Py_DECREF(diffs);
      return nullptr;
    }
    ddata[i] = (npy_int64)d;
  }
  return diffs;
}

/* consolidate(entries) -> (new_entries | None, insert_only)
 *
 * None as first element means "already consolidated as-is" (the cheap
 * precheck passed); insert_only reports unique-key all-positive shape. */
PyObject *consolidate(PyObject *, PyObject *args) {
  HIT(H_CONSOLIDATE);
  PyObject *entries;
  if (!PyArg_ParseTuple(args, "O!", &PyList_Type, &entries)) return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(entries);

  /* Precheck: all diffs > 0 and keys unique. */
  PyObject *seen = PySet_New(nullptr);
  if (!seen) return nullptr;
  bool clean = true;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    if (!PyTuple_Check(e) || PyTuple_GET_SIZE(e) != 3) {
      clean = false;
      break;
    }
    PyObject *key = PyTuple_GET_ITEM(e, 0);
    PyObject *diff = PyTuple_GET_ITEM(e, 2);
    long d = PyLong_AsLong(diff);
    if (d == -1 && PyErr_Occurred()) {
      Py_DECREF(seen);
      return nullptr;
    }
    if (d <= 0) {
      clean = false;
      break;
    }
    int contains = PySet_Contains(seen, key);
    if (contains < 0) {
      Py_DECREF(seen);
      return nullptr;
    }
    if (contains) {
      clean = false;
      break;
    }
    if (PySet_Add(seen, key) < 0) {
      Py_DECREF(seen);
      return nullptr;
    }
  }
  Py_DECREF(seen);
  if (clean) {
    return Py_BuildValue("(OO)", Py_None, Py_True);
  }

  /* Full path: merge duplicate (key, row) entries preserving first-seen
   * order, drop zero diffs. acc maps (key, row) -> [row, diff] — the dict
   * resolves hash collisions by row equality; unhashable rows fall back to
   * identity. */
  PyObject *acc = PyDict_New();
  PyObject *order = PyList_New(0);
  if (!acc || !order) {
    Py_XDECREF(acc);
    Py_XDECREF(order);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    PyObject *key = PyTuple_GET_ITEM(e, 0);
    PyObject *row = PyTuple_GET_ITEM(e, 1);
    PyObject *diff = PyTuple_GET_ITEM(e, 2);
    Py_hash_t rh = PyObject_Hash(row);
    PyObject *slot;
    if (rh == -1) {
      PyErr_Clear(); /* unhashable row: fall back to identity */
      slot = Py_BuildValue("(On)", key, (Py_ssize_t)(uintptr_t)row);
    } else {
      slot = PyTuple_Pack(2, key, row);
    }
    if (!slot) goto fail;
    {
      PyObject *found = PyDict_GetItemWithError(acc, slot);
      if (!found && PyErr_Occurred()) {
        Py_DECREF(slot);
        goto fail;
      }
      if (!found) {
        PyObject *pair = PyList_New(2);
        if (!pair) {
          Py_DECREF(slot);
          goto fail;
        }
        Py_INCREF(row);
        PyList_SET_ITEM(pair, 0, row);
        Py_INCREF(diff);
        PyList_SET_ITEM(pair, 1, diff);
        if (PyDict_SetItem(acc, slot, pair) < 0) {
          Py_DECREF(pair);
          Py_DECREF(slot);
          goto fail;
        }
        Py_DECREF(pair);
        if (PyList_Append(order, slot) < 0) {
          Py_DECREF(slot);
          goto fail;
        }
      } else {
        PyObject *old = PyList_GET_ITEM(found, 1);
        PyObject *sum = PyNumber_Add(old, diff);
        if (!sum) {
          Py_DECREF(slot);
          goto fail;
        }
        PyList_SetItem(found, 1, sum); /* steals sum */
      }
      Py_DECREF(slot);
    }
  }
  {
    PyObject *out = PyList_New(0);
    if (!out) goto fail;
    Py_ssize_t m = PyList_GET_SIZE(order);
    for (Py_ssize_t i = 0; i < m; i++) {
      PyObject *slot = PyList_GET_ITEM(order, i);
      PyObject *pair = PyDict_GetItemWithError(acc, slot);
      if (!pair) {
        Py_DECREF(out);
        goto fail;
      }
      PyObject *row = PyList_GET_ITEM(pair, 0);
      PyObject *diff = PyList_GET_ITEM(pair, 1);
      long d = PyLong_AsLong(diff);
      if (d == -1 && PyErr_Occurred()) {
        Py_DECREF(out);
        goto fail;
      }
      if (d != 0) {
        PyObject *entry =
            PyTuple_Pack(3, PyTuple_GET_ITEM(slot, 0), row, diff);
        if (!entry || PyList_Append(out, entry) < 0) {
          Py_XDECREF(entry);
          Py_DECREF(out);
          goto fail;
        }
        Py_DECREF(entry);
      }
    }
    Py_DECREF(acc);
    Py_DECREF(order);
    PyObject *res = Py_BuildValue("(NO)", out, Py_False);
    return res;
  }
fail:
  Py_DECREF(acc);
  Py_DECREF(order);
  return nullptr;
}

/* apply_state(state_dict, entries, insert_only) -> None
 * Mirrors batch.apply_batch_to_state. */
PyObject *apply_state(PyObject *, PyObject *args) {
  HIT(H_APPLY_STATE);
  PyObject *state, *entries;
  int insert_only;
  if (!PyArg_ParseTuple(args, "O!O!p", &PyDict_Type, &state, &PyList_Type,
                        &entries, &insert_only))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(entries);
  if (insert_only) {
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject *e = PyList_GET_ITEM(entries, i);
      if (PyDict_SetItem(state, PyTuple_GET_ITEM(e, 0),
                         PyTuple_GET_ITEM(e, 1)) < 0)
        return nullptr;
    }
    Py_RETURN_NONE;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    long d = PyLong_AsLong(PyTuple_GET_ITEM(e, 2));
    if (d == -1 && PyErr_Occurred()) return nullptr;
    if (d < 0) {
      if (PyDict_DelItem(state, PyTuple_GET_ITEM(e, 0)) < 0) PyErr_Clear();
    }
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    long d = PyLong_AsLong(PyTuple_GET_ITEM(e, 2));
    if (d == -1 && PyErr_Occurred()) return nullptr;
    if (d > 0) {
      if (PyDict_SetItem(state, PyTuple_GET_ITEM(e, 0),
                         PyTuple_GET_ITEM(e, 1)) < 0)
        return nullptr;
    }
  }
  Py_RETURN_NONE;
}

/* build_entries(entries, columns) -> list
 * New entries with rows rebuilt from per-column Python lists (the tail of
 * the columnar expression path): row_i = (columns[0][i], columns[1][i],…),
 * keys/diffs reused from the input entries. */
PyObject *build_entries(PyObject *, PyObject *args) {
  HIT(H_BUILD_ENTRIES);
  PyObject *entries, *columns;
  if (!PyArg_ParseTuple(args, "O!O!", &PyList_Type, &entries, &PyList_Type,
                        &columns))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(entries);
  Py_ssize_t ncols = PyList_GET_SIZE(columns);
  for (Py_ssize_t c = 0; c < ncols; c++) {
    PyObject *col = PyList_GET_ITEM(columns, c);
    if (!PyList_Check(col) || PyList_GET_SIZE(col) != n) {
      PyErr_SetString(PyExc_ValueError, "column length mismatch");
      return nullptr;
    }
  }
  PyObject *out = PyList_New(n);
  if (!out) return nullptr;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    PyObject *row = PyTuple_New(ncols);
    if (!row) {
      Py_DECREF(out);
      return nullptr;
    }
    for (Py_ssize_t c = 0; c < ncols; c++) {
      PyObject *v = PyList_GET_ITEM(PyList_GET_ITEM(columns, c), i);
      Py_INCREF(v);
      PyTuple_SET_ITEM(row, c, v);
    }
    PyObject *entry =
        PyTuple_Pack(3, PyTuple_GET_ITEM(e, 0), row, PyTuple_GET_ITEM(e, 2));
    Py_DECREF(row);
    if (!entry) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, entry);
  }
  return out;
}

/* filter_truthy(entries, col) -> (list | None)
 * Keep entries whose row[col] is truthy. Returns None (for Python-side
 * fallback) if any condition value is not a plain bool — error poisoning
 * and odd truthiness keep their row-wise semantics. */
PyObject *filter_truthy(PyObject *, PyObject *args) {
  HIT(H_FILTER_TRUTHY);
  PyObject *entries;
  Py_ssize_t col;
  if (!PyArg_ParseTuple(args, "O!n", &PyList_Type, &entries, &col))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(entries);
  PyObject *out = PyList_New(0);
  if (!out) return nullptr;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    PyObject *row = PyTuple_GET_ITEM(e, 1);
    if (!PyTuple_Check(row) || PyTuple_GET_SIZE(row) <= col) {
      Py_DECREF(out);
      Py_RETURN_NONE;
    }
    PyObject *v = PyTuple_GET_ITEM(row, col);
    if (v == Py_True) {
      if (PyList_Append(out, e) < 0) {
        Py_DECREF(out);
        return nullptr;
      }
    } else if (v != Py_False) {
      Py_DECREF(out);
      Py_RETURN_NONE; /* non-bool condition: row-wise semantics */
    }
  }
  return out;
}

/* -- blake2b (RFC 7693) for join result keys ---------------------------------
 *
 * Digest-identical to engine/value.py hash_values: digest_size=16,
 * personal "pw-tpu-key", message = salt + per-value tagged bytes. Only the
 * Pointer-pair message shape is produced here (join_result_key), so the
 * implementation is the compact single-purpose core, not a general hash
 * library.
 */

const uint64_t B2B_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

const uint8_t B2B_SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

void b2b_compress(uint64_t h[8], const uint8_t block[128], uint64_t t,
                  int last) {
  uint64_t v[16], m[16];
  for (int i = 0; i < 8; i++) v[i] = h[i];
  for (int i = 0; i < 8; i++) v[i + 8] = B2B_IV[i];
  v[12] ^= t; /* low counter word; messages here are far below 2^64 */
  if (last) v[14] = ~v[14];
  memcpy(m, block, 128); /* little-endian host assumed (x86/arm64) */
  for (int r = 0; r < 12; r++) {
    const uint8_t *s = B2B_SIGMA[r];
#define B2B_G(a, b, c, d, x, y)                 \
  v[a] = v[a] + v[b] + (x);                     \
  v[d] = rotr64(v[d] ^ v[a], 32);               \
  v[c] = v[c] + v[d];                           \
  v[b] = rotr64(v[b] ^ v[c], 24);               \
  v[a] = v[a] + v[b] + (y);                     \
  v[d] = rotr64(v[d] ^ v[a], 16);               \
  v[c] = v[c] + v[d];                           \
  v[b] = rotr64(v[b] ^ v[c], 63);
    B2B_G(0, 4, 8, 12, m[s[0]], m[s[1]]);
    B2B_G(1, 5, 9, 13, m[s[2]], m[s[3]]);
    B2B_G(2, 6, 10, 14, m[s[4]], m[s[5]]);
    B2B_G(3, 7, 11, 15, m[s[6]], m[s[7]]);
    B2B_G(0, 5, 10, 15, m[s[8]], m[s[9]]);
    B2B_G(1, 6, 11, 12, m[s[10]], m[s[11]]);
    B2B_G(2, 7, 8, 13, m[s[12]], m[s[13]]);
    B2B_G(3, 4, 9, 14, m[s[14]], m[s[15]]);
#undef B2B_G
  }
  for (int i = 0; i < 8; i++) h[i] ^= v[i] ^ v[i + 8];
}

/* blake2b-128 of a short (<=128 byte) message with personal "pw-tpu-key". */
void b2b16_short(const uint8_t *msg, size_t len, uint8_t out[16]) {
  uint64_t h[8];
  for (int i = 0; i < 8; i++) h[i] = B2B_IV[i];
  /* param block: digest_length=16, fanout=1, depth=1, personal @48 */
  uint8_t param[64] = {0};
  param[0] = 16;
  param[2] = 1;
  param[3] = 1;
  memcpy(param + 48, "pw-tpu-key", 10);
  uint64_t pw[8];
  memcpy(pw, param, 64);
  for (int i = 0; i < 8; i++) h[i] ^= pw[i];
  uint8_t block[128] = {0};
  memcpy(block, msg, len);
  b2b_compress(h, block, (uint64_t)len, 1);
  memcpy(out, h, 16);
}

/* -- insert-only inner-join delta --------------------------------------------
 *
 * The C floor under JoinNode._process_insert_only_inner (engine/graph.py):
 * ΔR pairs against the pre-delta left arrangement, then ΔL against the
 * post-delta right arrangement. Join keys limited to scalar types the
 * Python _jk would hash unchanged (int/bool/float/str/bytes incl.
 * subclasses like Pointer); anything else — or an ERROR cell — bails to
 * the Python path BEFORE mutating either arrangement.
 */

int jk_value_ok(PyObject *v, PyObject *error_obj) {
  if (v == error_obj) return 0;
  return PyLong_Check(v) || PyFloat_Check(v) || PyUnicode_Check(v) ||
         PyBytes_Check(v);
}

/* row key (Pointer int) -> 16 little-endian bytes; -1 on failure */
int key_bytes(PyObject *key, uint8_t out[16]) {
  if (!PyLong_Check(key)) return -1;
#if PY_VERSION_HEX >= 0x030d0000
  if (PyLong_AsNativeBytes(key, out, 16,
                           Py_ASNATIVEBYTES_LITTLE_ENDIAN |
                               Py_ASNATIVEBYTES_UNSIGNED_BUFFER) < 0)
    return -1;
#else
  if (_PyLong_AsByteArray((PyLongObject *)key, out, 16, 1, 0) < 0) return -1;
#endif
  return 0;
}

/* blake2b16("join" + 0x04 lkey16 + 0x04 rkey16) -> new Pointer.
 * Pointer construction goes through int.__new__ (PyLong_Type.tp_new)
 * directly: the digest is 128 bits by construction, so the Python-level
 * Pointer.__new__ masking wrapper adds nothing but a frame per pair
 * (measured >50% of the join kernel's time). */
PyObject *join_pair_key(PyObject *pointer_type, const uint8_t lk[16],
                        const uint8_t rk[16]) {
  uint8_t msg[4 + 17 + 17];
  memcpy(msg, "join", 4);
  msg[4] = 0x04; /* _H_POINTER */
  memcpy(msg + 5, lk, 16);
  msg[21] = 0x04;
  memcpy(msg + 22, rk, 16);
  uint8_t digest[16];
  b2b16_short(msg, sizeof(msg), digest);
  PyObject *as_int = _PyLong_FromByteArray(digest, 16, 1, 0);
  if (!as_int) return nullptr;
  /* thread-safe without locking: the GIL is held throughout */
  static PyObject *argtuple = nullptr;
  if (!argtuple || Py_REFCNT(argtuple) != 1) {
    argtuple = PyTuple_New(1);
    if (!argtuple) {
      Py_DECREF(as_int);
      return nullptr;
    }
  } else {
    Py_XDECREF(PyTuple_GET_ITEM(argtuple, 0));
  }
  PyTuple_SET_ITEM(argtuple, 0, as_int);
  PyObject *ptr =
      PyLong_Type.tp_new((PyTypeObject *)pointer_type, argtuple, nullptr);
  return ptr;
}

/* build the join-key tuple for one row; NULL with no error set = bail */
PyObject *make_jk(PyObject *row, PyObject *cols, PyObject *error_obj) {
  Py_ssize_t k = PyList_GET_SIZE(cols);
  PyObject *jk = PyTuple_New(k);
  if (!jk) return nullptr;
  for (Py_ssize_t c = 0; c < k; c++) {
    Py_ssize_t idx = PyLong_AsSsize_t(PyList_GET_ITEM(cols, c));
    if (!PyTuple_Check(row) || PyTuple_GET_SIZE(row) <= idx) {
      Py_DECREF(jk);
      return nullptr;
    }
    PyObject *v = PyTuple_GET_ITEM(row, idx);
    if (!jk_value_ok(v, error_obj)) {
      Py_DECREF(jk);
      return nullptr;
    }
    Py_INCREF(v);
    PyTuple_SET_ITEM(jk, c, v);
  }
  return jk;
}

/* pair every arranged row of `group` with (key,row); append to out and
 * mirror into `current` (the node state) */
int emit_pairs(PyObject *out, PyObject *group, PyObject *key, PyObject *row,
               int row_is_left, PyObject *pointer_type, PyObject *one,
               PyObject *current, PyObject *jrk_fn) {
  uint8_t kb[16];
  if (key_bytes(key, kb) < 0) return -1;
  PyObject *gk, *grow;
  Py_ssize_t pos = 0;
  while (PyDict_Next(group, &pos, &gk, &grow)) {
    PyObject *okey;
    if ((PyObject *)Py_TYPE(gk) == pointer_type) {
      uint8_t gb[16];
      if (key_bytes(gk, gb) < 0) return -1;
      okey = row_is_left ? join_pair_key(pointer_type, kb, gb)
                         : join_pair_key(pointer_type, gb, kb);
    } else {
      /* arrangement rows from an earlier bailed (Python-path) batch may
       * carry non-Pointer keys; route those pairs through the Python
       * join_result_key so both paths agree on result identity */
      okey = row_is_left
                 ? PyObject_CallFunctionObjArgs(jrk_fn, key, gk, nullptr)
                 : PyObject_CallFunctionObjArgs(jrk_fn, gk, key, nullptr);
    }
    if (!okey) return -1;
    PyObject *orow = row_is_left ? PySequence_Concat(row, grow)
                                 : PySequence_Concat(grow, row);
    if (!orow) {
      Py_DECREF(okey);
      return -1;
    }
    PyObject *entry = PyTuple_Pack(3, okey, orow, one);
    int rc = entry ? PyList_Append(out, entry) : -1;
    Py_XDECREF(entry);
    /* current == None: caller defers state application (lazy node state) */
    if (rc == 0 && current != Py_None)
      rc = PyDict_SetItem(current, okey, orow);
    Py_DECREF(okey);
    Py_DECREF(orow);
    if (rc < 0) return -1;
  }
  return 0;
}

/* one side of the delta: pair each entry against `probe_arr`, then insert
 * it into `build_arr`. Returns 0 ok, -1 error (error set). */
int join_side(PyObject *entries, PyObject *cols, PyObject *probe_arr,
              PyObject *build_arr, PyObject *out, int is_left,
              PyObject *error_obj, PyObject *pointer_type, PyObject *one,
              PyObject *current, PyObject *jrk_fn) {
  Py_ssize_t n = PyList_GET_SIZE(entries);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    PyObject *key = PyTuple_GET_ITEM(e, 0);
    PyObject *row = PyTuple_GET_ITEM(e, 1);
    PyObject *jk = make_jk(row, cols, error_obj);
    if (!jk) return -1; /* prescan guarantees this cannot happen */
    PyObject *group = PyDict_GetItem(probe_arr, jk);
    if (group && PyDict_Check(group) &&
        emit_pairs(out, group, key, row, is_left, pointer_type, one,
                   current, jrk_fn) < 0) {
      Py_DECREF(jk);
      return -1;
    }
    PyObject *build_group = PyDict_GetItem(build_arr, jk);
    if (!build_group) {
      build_group = PyDict_New();
      if (!build_group || PyDict_SetItem(build_arr, jk, build_group) < 0) {
        Py_XDECREF(build_group);
        Py_DECREF(jk);
        return -1;
      }
      Py_DECREF(build_group); /* arr holds it */
    }
    if (PyDict_SetItem(build_group, key, row) < 0) {
      Py_DECREF(jk);
      return -1;
    }
    Py_DECREF(jk);
  }
  return 0;
}

/* every entry well-formed, keys EXACTLY Pointer, jk cells scalar
 * non-ERROR? Exact-Pointer matters: join_pair_key tags keys _H_POINTER
 * unsigned-16LE, which only matches Python's hash_values for genuine
 * Pointers — a plain (possibly negative) int key must bail to Python so
 * the fast and general paths derive identical result keys. */
int join_prescan(PyObject *entries, PyObject *cols, PyObject *error_obj,
                 PyObject *pointer_type) {
  Py_ssize_t n = PyList_GET_SIZE(entries);
  Py_ssize_t k = PyList_GET_SIZE(cols);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    if (!PyTuple_Check(e) || PyTuple_GET_SIZE(e) != 3) return 0;
    if ((PyObject *)Py_TYPE(PyTuple_GET_ITEM(e, 0)) != pointer_type)
      return 0;
    /* diff must be exactly 1: insert-only batches may legally carry
     * multiplicities > 1, which this pair-emitting kernel (and the dict
     * arrangements, which drop multiplicity) cannot represent */
    PyObject *diff = PyTuple_GET_ITEM(e, 2);
    if (!PyLong_Check(diff) || PyLong_AsLong(diff) != 1) return 0;
    PyObject *row = PyTuple_GET_ITEM(e, 1);
    if (!PyTuple_Check(row)) return 0;
    for (Py_ssize_t c = 0; c < k; c++) {
      Py_ssize_t idx = PyLong_AsSsize_t(PyList_GET_ITEM(cols, c));
      if (idx < 0 || PyTuple_GET_SIZE(row) <= idx) return 0;
      if (!jk_value_ok(PyTuple_GET_ITEM(row, idx), error_obj)) return 0;
    }
  }
  return 1;
}

/* join_insert_inner(left_entries, right_entries, left_on, right_on,
 *                   left_arr, right_arr, error_obj, pointer_type, current)
 *   -> entries list | None (bail to Python; arrangements untouched).
 * `current` (the node's key->row state) is written alongside emission, so
 * the scheduler's apply_batch_to_state pass is skipped (_preapplied). */
PyObject *join_insert_inner(PyObject *, PyObject *args) {
  HIT(H_JOIN_INSERT_INNER);
  PyObject *le, *re, *lon, *ron, *larr, *rarr, *error_obj, *pointer_type,
      *current, *jrk_fn;
  if (!PyArg_ParseTuple(args, "O!O!O!O!O!O!OOOO", &PyList_Type, &le,
                        &PyList_Type, &re, &PyList_Type, &lon, &PyList_Type,
                        &ron, &PyDict_Type, &larr, &PyDict_Type, &rarr,
                        &error_obj, &pointer_type, &current, &jrk_fn))
    return nullptr;
  if (current != Py_None && !PyDict_Check(current)) {
    PyErr_SetString(PyExc_TypeError, "current must be a dict or None");
    return nullptr;
  }
  if (!PyType_Check(pointer_type) ||
      !PyType_IsSubtype((PyTypeObject *)pointer_type, &PyLong_Type))
    Py_RETURN_NONE; /* tp_new shortcut requires an int subclass */
  if (!join_prescan(le, lon, error_obj, pointer_type) ||
      !join_prescan(re, ron, error_obj, pointer_type))
    Py_RETURN_NONE;
  PyObject *out = PyList_New(0);
  if (!out) return nullptr;
  PyObject *one = PyLong_FromLong(1);
  /* ΔR probes the pre-delta left arrangement and lands in right_arr... */
  if (join_side(re, ron, larr, rarr, out, 0, error_obj, pointer_type, one,
                current, jrk_fn) < 0 ||
      /* ...then ΔL probes the post-delta right arrangement */
      join_side(le, lon, rarr, larr, out, 1, error_obj, pointer_type, one,
                current, jrk_fn) < 0) {
    Py_DECREF(out);
    Py_DECREF(one);
    return nullptr;
  }
  Py_DECREF(one);
  return out;
}

/* -- columnar key plumbing ---------------------------------------------------
 *
 * The columnar DeltaBatch (engine/batch.py Columns) stores keys as a
 * (n,16) little-endian byte matrix; these kernels convert to/from the
 * Pointer-object view and derive join result keys vectorized — one C
 * pass instead of per-row hashlib + int.to_bytes.
 */

/* make a Pointer (int subclass) from 16 LE bytes via tp_new, skipping the
 * Python-level __new__ masking wrapper (the digest is already 128-bit) */
PyObject *pointer_from_bytes(PyTypeObject *pointer_type,
                             const uint8_t b[16]) {
  PyObject *as_int = _PyLong_FromByteArray(b, 16, 1, 0);
  if (!as_int) return nullptr;
  PyObject *argtuple = PyTuple_New(1);
  if (!argtuple) {
    Py_DECREF(as_int);
    return nullptr;
  }
  PyTuple_SET_ITEM(argtuple, 0, as_int);
  PyObject *ptr = PyLong_Type.tp_new(pointer_type, argtuple, nullptr);
  Py_DECREF(argtuple);
  return ptr;
}

/* pointers_to_bytes(keys_list) -> (n,16) uint8 ndarray | None (non-int) */
PyObject *pointers_to_bytes(PyObject *, PyObject *args) {
  HIT(H_POINTERS_TO_BYTES);
  PyObject *keys;
  if (!PyArg_ParseTuple(args, "O!", &PyList_Type, &keys)) return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(keys);
  npy_intp dims[2] = {n, 16};
  PyObject *arr = PyArray_SimpleNew(2, dims, NPY_UINT8);
  if (!arr) return nullptr;
  uint8_t *data = (uint8_t *)PyArray_BYTES((PyArrayObject *)arr);
  for (Py_ssize_t i = 0; i < n; i++) {
    if (key_bytes(PyList_GET_ITEM(keys, i), data + i * 16) < 0) {
      Py_DECREF(arr);
      if (!PyErr_Occurred()) Py_RETURN_NONE;
      return nullptr;
    }
  }
  return arr;
}

/* bytes_to_pointers(arr, pointer_type) -> list of Pointer */
PyObject *bytes_to_pointers(PyObject *, PyObject *args) {
  HIT(H_BYTES_TO_POINTERS);
  PyObject *arr_obj, *pointer_type;
  if (!PyArg_ParseTuple(args, "O!O", &PyArray_Type, &arr_obj, &pointer_type))
    return nullptr;
  if (!PyType_Check(pointer_type) ||
      !PyType_IsSubtype((PyTypeObject *)pointer_type, &PyLong_Type)) {
    PyErr_SetString(PyExc_TypeError, "pointer_type must subclass int");
    return nullptr;
  }
  PyArrayObject *arr = (PyArrayObject *)arr_obj;
  if (PyArray_NDIM(arr) != 2 || PyArray_DIM(arr, 1) != 16 ||
      PyArray_TYPE(arr) != NPY_UINT8 ||
      !PyArray_IS_C_CONTIGUOUS(arr)) {
    PyErr_SetString(PyExc_ValueError, "expected C-contiguous (n,16) uint8");
    return nullptr;
  }
  Py_ssize_t n = PyArray_DIM(arr, 0);
  const uint8_t *data = (const uint8_t *)PyArray_BYTES(arr);
  PyObject *out = PyList_New(n);
  if (!out) return nullptr;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *p = pointer_from_bytes((PyTypeObject *)pointer_type,
                                     data + i * 16);
    if (!p) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, p);
  }
  return out;
}

/* entry_keys_bytes(entries, pointer_type) -> (n,16) uint8 | None.
 * None when any key is not EXACTLY a Pointer (subclass tagging matters:
 * hash_join_pairs tags _H_POINTER, which only matches hash_values for
 * genuine Pointers). */
PyObject *entry_keys_bytes(PyObject *, PyObject *args) {
  HIT(H_ENTRY_KEYS_BYTES);
  PyObject *entries, *pointer_type;
  if (!PyArg_ParseTuple(args, "O!O", &PyList_Type, &entries, &pointer_type))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(entries);
  npy_intp dims[2] = {n, 16};
  PyObject *arr = PyArray_SimpleNew(2, dims, NPY_UINT8);
  if (!arr) return nullptr;
  uint8_t *data = (uint8_t *)PyArray_BYTES((PyArrayObject *)arr);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    if (!PyTuple_Check(e) || PyTuple_GET_SIZE(e) != 3) {
      Py_DECREF(arr);
      Py_RETURN_NONE;
    }
    PyObject *key = PyTuple_GET_ITEM(e, 0);
    if ((PyObject *)Py_TYPE(key) != pointer_type ||
        key_bytes(key, data + i * 16) < 0) {
      Py_DECREF(arr);
      if (PyErr_Occurred()) return nullptr;
      Py_RETURN_NONE;
    }
  }
  return arr;
}

/* hash_join_pairs(lbytes, rbytes) -> (n,16) uint8 of
 * blake2b16("join" + 0x04 lk + 0x04 rk) — vectorized join_result_key. */
PyObject *hash_join_pairs(PyObject *, PyObject *args) {
  HIT(H_HASH_JOIN_PAIRS);
  PyObject *l_obj, *r_obj;
  if (!PyArg_ParseTuple(args, "O!O!", &PyArray_Type, &l_obj, &PyArray_Type,
                        &r_obj))
    return nullptr;
  PyArrayObject *l = (PyArrayObject *)l_obj, *r = (PyArrayObject *)r_obj;
  PyArrayObject *pair[2] = {l, r};
  for (int side = 0; side < 2; side++) {
    PyArrayObject *a = pair[side];
    if (PyArray_NDIM(a) != 2 || PyArray_DIM(a, 1) != 16 ||
        PyArray_TYPE(a) != NPY_UINT8 || !PyArray_IS_C_CONTIGUOUS(a)) {
      PyErr_SetString(PyExc_ValueError,
                      "expected C-contiguous (n,16) uint8");
      return nullptr;
    }
  }
  if (PyArray_DIM(l, 0) != PyArray_DIM(r, 0)) {
    PyErr_SetString(PyExc_ValueError, "length mismatch");
    return nullptr;
  }
  Py_ssize_t n = PyArray_DIM(l, 0);
  npy_intp dims[2] = {n, 16};
  PyObject *out = PyArray_SimpleNew(2, dims, NPY_UINT8);
  if (!out) return nullptr;
  const uint8_t *lb = (const uint8_t *)PyArray_BYTES(l);
  const uint8_t *rb = (const uint8_t *)PyArray_BYTES(r);
  uint8_t *ob = (uint8_t *)PyArray_BYTES((PyArrayObject *)out);
  uint8_t msg[4 + 17 + 17];
  memcpy(msg, "join", 4);
  msg[4] = 0x04; /* _H_POINTER */
  msg[21] = 0x04;
  for (Py_ssize_t i = 0; i < n; i++) {
    memcpy(msg + 5, lb + i * 16, 16);
    memcpy(msg + 22, rb + i * 16, 16);
    b2b16_short(msg, sizeof(msg), ob + i * 16);
  }
  return out;
}

/* one cell of a 1-D column array -> new reference */
PyObject *cell_to_object(PyArrayObject *col, Py_ssize_t i) {
  switch (PyArray_TYPE(col)) {
    case NPY_INT64:
      return PyLong_FromLongLong(*(npy_int64 *)PyArray_GETPTR1(col, i));
    case NPY_FLOAT64:
      return PyFloat_FromDouble(*(npy_double *)PyArray_GETPTR1(col, i));
    case NPY_BOOL: {
      PyObject *v = *(npy_bool *)PyArray_GETPTR1(col, i) ? Py_True : Py_False;
      Py_INCREF(v);
      return v;
    }
    case NPY_OBJECT: {
      PyObject *v = *(PyObject **)PyArray_GETPTR1(col, i);
      Py_INCREF(v);
      return v;
    }
    default:
      /* strings / datetimes / anything else: generic numpy conversion */
      return PyArray_GETITEM(col, (const char *)PyArray_GETPTR1(col, i));
  }
}

/* columns_to_entries(keys_list, cols_list, diffs|None) -> entries list.
 * cols_list: 1-D ndarrays, one per column; diffs: int64 ndarray or None. */
PyObject *columns_to_entries(PyObject *, PyObject *args) {
  HIT(H_COLUMNS_TO_ENTRIES);
  PyObject *keys, *cols, *diffs_obj;
  if (!PyArg_ParseTuple(args, "O!O!O", &PyList_Type, &keys, &PyList_Type,
                        &cols, &diffs_obj))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(keys);
  Py_ssize_t arity = PyList_GET_SIZE(cols);
  const npy_int64 *diffs = nullptr;
  if (diffs_obj != Py_None) {
    if (!PyArray_Check(diffs_obj) ||
        PyArray_TYPE((PyArrayObject *)diffs_obj) != NPY_INT64 ||
        PyArray_NDIM((PyArrayObject *)diffs_obj) != 1 ||
        PyArray_DIM((PyArrayObject *)diffs_obj, 0) != n ||
        !PyArray_IS_C_CONTIGUOUS((PyArrayObject *)diffs_obj)) {
      PyErr_SetString(PyExc_ValueError, "diffs must be contiguous int64[n]");
      return nullptr;
    }
    diffs = (const npy_int64 *)PyArray_BYTES((PyArrayObject *)diffs_obj);
  }
  for (Py_ssize_t c = 0; c < arity; c++) {
    PyObject *col = PyList_GET_ITEM(cols, c);
    if (!PyArray_Check(col) || PyArray_NDIM((PyArrayObject *)col) != 1 ||
        PyArray_DIM((PyArrayObject *)col, 0) != n) {
      PyErr_SetString(PyExc_ValueError, "columns must be 1-D ndarrays[n]");
      return nullptr;
    }
  }
  PyObject *out = PyList_New(n);
  if (!out) return nullptr;
  PyObject *one = PyLong_FromLong(1);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *row = PyTuple_New(arity);
    if (!row) goto fail;
    for (Py_ssize_t c = 0; c < arity; c++) {
      PyObject *v =
          cell_to_object((PyArrayObject *)PyList_GET_ITEM(cols, c), i);
      if (!v) {
        Py_DECREF(row);
        goto fail;
      }
      PyTuple_SET_ITEM(row, c, v);
    }
    PyObject *diff;
    if (diffs) {
      diff = PyLong_FromLongLong(diffs[i]);
      if (!diff) {
        Py_DECREF(row);
        goto fail;
      }
    } else {
      diff = one;
      Py_INCREF(one);
    }
    PyObject *key = PyList_GET_ITEM(keys, i);
    Py_INCREF(key);
    PyObject *entry = PyTuple_New(3);
    if (!entry) {
      Py_DECREF(row);
      Py_DECREF(diff);
      Py_DECREF(key);
      goto fail;
    }
    PyTuple_SET_ITEM(entry, 0, key);
    PyTuple_SET_ITEM(entry, 1, row);
    PyTuple_SET_ITEM(entry, 2, diff);
    PyList_SET_ITEM(out, i, entry);
  }
  Py_DECREF(one);
  return out;
fail:
  Py_DECREF(one);
  Py_DECREF(out);
  return nullptr;
}

/* -- gen-2 kernels: batched digests, shard coding, side extraction -----------
 *
 * Everything below is digest- or result-identical to a pure-Python
 * implementation that stays in the tree (engine/value.py `_digest16`/
 * `_feed`, engine/routing.py `_shard_of`, graph.py `_side_from_batch` /
 * `_match_join_pairs_multi`, `InputSession.flush`): the kernels bail —
 * Py_RETURN_NONE, or a per-item Python fallback callable — the moment a
 * value leaves the exact-type fast set, so the Python path remains THE
 * definition of behavior and the property suite can assert bit equality.
 */

/* Streaming blake2b-128 (digest_size=16, personal "pw-tpu-key"): the
 * b2b16_short core above only handles <=128-byte messages; value tuples
 * (strings, nested tuples) need the full chunked update loop. */
struct B2BCtx {
  uint64_t h[8];
  uint64_t t;       /* bytes fed into compress so far (incl. current) */
  size_t buflen;
  uint8_t buf[128];
};

void b2b_init(B2BCtx *c) {
  for (int i = 0; i < 8; i++) c->h[i] = B2B_IV[i];
  uint8_t param[64] = {0};
  param[0] = 16;
  param[2] = 1;
  param[3] = 1;
  memcpy(param + 48, "pw-tpu-key", 10);
  uint64_t pw[8];
  memcpy(pw, param, 64);
  for (int i = 0; i < 8; i++) c->h[i] ^= pw[i];
  c->t = 0;
  c->buflen = 0;
}

void b2b_update(B2BCtx *c, const uint8_t *data, size_t len) {
  while (len > 0) {
    if (c->buflen == 128) {
      /* flush a full buffer only when more input follows — the final
       * block must go through b2b_final with the last flag set */
      c->t += 128;
      b2b_compress(c->h, c->buf, c->t, 0);
      c->buflen = 0;
    }
    size_t take = 128 - c->buflen;
    if (take > len) take = len;
    memcpy(c->buf + c->buflen, data, take);
    c->buflen += take;
    data += take;
    len -= take;
  }
}

void b2b_final(B2BCtx *c, uint8_t out[16]) {
  c->t += c->buflen;
  memset(c->buf + c->buflen, 0, 128 - c->buflen);
  b2b_compress(c->h, c->buf, c->t, 1);
  memcpy(out, c->h, 16);
}

/* arbitrary PyLong -> 16-byte signed little-endian, matching
 * int.to_bytes(16, "little", signed=True) including the OverflowError. */
int long_to_signed16(PyObject *v, uint8_t out[16]) {
  int overflow = 0;
  long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
  if (!overflow) {
    if (x == -1 && PyErr_Occurred()) return -1;
    memcpy(out, &x, 8);
    memset(out + 8, x < 0 ? 0xff : 0x00, 8);
    return 0;
  }
#if PY_VERSION_HEX >= 0x030d0000
  Py_ssize_t r = PyLong_AsNativeBytes(v, out, 16,
                                      Py_ASNATIVEBYTES_LITTLE_ENDIAN);
  if (r < 0) return -1;
  if (r > 16) {
    PyErr_SetString(PyExc_OverflowError, "int too big to convert");
    return -1;
  }
  return 0;
#else
  return _PyLong_AsByteArray((PyLongObject *)v, out, 16, 1, 1);
#endif
}

/* Feed one value's tagged serialization (engine/value.py `_feed` /
 * `_digest16` byte stream) into the hash context.
 * Returns 0 = fed, 1 = bail to the Python fallback (type outside the
 * exact fast set), -1 = Python error set (propagates, matching the
 * exception the Python path would raise: OverflowError on >128-bit
 * ints, UnicodeEncodeError on surrogates). */
int feed_value(B2BCtx *c, PyObject *v, PyObject *pointer_type,
               PyObject *error_obj, int depth) {
  if (depth > 32) return 1; /* pathological nesting: Python recursion rules */
  PyTypeObject *t = Py_TYPE(v);
  if ((PyObject *)t == pointer_type) {
    uint8_t b[17];
    b[0] = 0x04; /* _H_POINTER */
    if (key_bytes(v, b + 1) < 0) return -1;
    b2b_update(c, b, 17);
    return 0;
  }
  if (v == Py_None) {
    uint8_t b = 0x00; /* _H_NONE */
    b2b_update(c, &b, 1);
    return 0;
  }
  if (v == error_obj) {
    uint8_t b = 0x0d; /* _H_ERROR */
    b2b_update(c, &b, 1);
    return 0;
  }
  if (t == &PyBool_Type) {
    uint8_t b[2] = {0x01, (uint8_t)(v == Py_True ? 1 : 0)};
    b2b_update(c, b, 2);
    return 0;
  }
  if (t == &PyLong_Type) {
    uint8_t b[17];
    b[0] = 0x02; /* _H_INT */
    if (long_to_signed16(v, b + 1) < 0) return -1;
    b2b_update(c, b, 17);
    return 0;
  }
  if (t == &PyFloat_Type) {
    double f = PyFloat_AS_DOUBLE(v);
    uint8_t b[17];
    if (f == f && !std::isinf(f) && std::fabs(f) < 9223372036854775808.0 &&
        std::trunc(f) == f) {
      /* integral in-range floats hash as ints (engine equality) */
      b[0] = 0x02;
      long long x = (long long)f;
      memcpy(b + 1, &x, 8);
      memset(b + 9, x < 0 ? 0xff : 0x00, 8);
      b2b_update(c, b, 17);
    } else {
      b[0] = 0x03; /* _H_FLOAT */
      memcpy(b + 1, &f, 8);
      b2b_update(c, b, 9);
    }
    return 0;
  }
  if (t == &PyUnicode_Type) {
    Py_ssize_t len;
    const char *s = PyUnicode_AsUTF8AndSize(v, &len);
    if (!s) return -1;
    uint8_t hdr[9];
    hdr[0] = 0x05; /* _H_STRING */
    uint64_t l = (uint64_t)len;
    memcpy(hdr + 1, &l, 8);
    b2b_update(c, hdr, 9);
    b2b_update(c, (const uint8_t *)s, (size_t)len);
    return 0;
  }
  if (t == &PyBytes_Type) {
    uint8_t hdr[9];
    hdr[0] = 0x06; /* _H_BYTES */
    uint64_t l = (uint64_t)PyBytes_GET_SIZE(v);
    memcpy(hdr + 1, &l, 8);
    b2b_update(c, hdr, 9);
    b2b_update(c, (const uint8_t *)PyBytes_AS_STRING(v), (size_t)l);
    return 0;
  }
  if (t == &PyTuple_Type || t == &PyList_Type) {
    int is_tuple = t == &PyTuple_Type;
    Py_ssize_t sz = is_tuple ? PyTuple_GET_SIZE(v) : PyList_GET_SIZE(v);
    uint8_t hdr[9];
    hdr[0] = 0x07; /* _H_TUPLE */
    uint64_t l = (uint64_t)sz;
    memcpy(hdr + 1, &l, 8);
    b2b_update(c, hdr, 9);
    for (Py_ssize_t i = 0; i < sz; i++) {
      PyObject *item =
          is_tuple ? PyTuple_GET_ITEM(v, i) : PyList_GET_ITEM(v, i);
      int rc = feed_value(c, item, pointer_type, error_obj, depth + 1);
      if (rc != 0) return rc;
    }
    return 0;
  }
  /* ndarray / datetime / Json / wrapper / np scalars / subclasses:
   * the Python serializer owns these */
  return 1;
}

/* call the per-item Python fallback; must return exactly 16 bytes */
int fallback_digest(PyObject *fallback, PyObject *item, uint8_t out[16]) {
  PyObject *d = PyObject_CallFunctionObjArgs(fallback, item, nullptr);
  if (!d) return -1;
  if (!PyBytes_Check(d) || PyBytes_GET_SIZE(d) != 16) {
    Py_DECREF(d);
    PyErr_SetString(PyExc_ValueError, "fallback must return 16 bytes");
    return -1;
  }
  memcpy(out, PyBytes_AS_STRING(d), 16);
  Py_DECREF(d);
  return 0;
}

/* hash_tuples_batch(rows, salt, bare, Pointer, ERROR, fallback)
 *   -> (n,16) uint8 digest matrix.
 * rows: list (or 1-D object ndarray) of value tuples — or of bare values
 * when bare is true (the object-column coding path hands the column array
 * straight in; no [(v,) for v in col.tolist()] materialization).
 * fallback(item) -> bytes16 computes any row the native serializer
 * cannot, carrying the caller's on_type_error semantics. */
PyObject *hash_tuples_batch(PyObject *, PyObject *args) {
  HIT(H_HASH_TUPLES_BATCH);
  PyObject *rows, *salt_obj, *pointer_type, *error_obj, *fallback;
  int bare;
  if (!PyArg_ParseTuple(args, "OO!pOOO", &rows, &PyBytes_Type, &salt_obj,
                        &bare, &pointer_type, &error_obj, &fallback))
    return nullptr;
  Py_ssize_t n;
  int is_list = PyList_Check(rows);
  PyObject **items = nullptr;
  if (is_list) {
    n = PyList_GET_SIZE(rows);
  } else if (PyArray_Check(rows)) {
    PyArrayObject *a = (PyArrayObject *)rows;
    if (PyArray_TYPE(a) != NPY_OBJECT || PyArray_NDIM(a) != 1 ||
        !PyArray_IS_C_CONTIGUOUS(a)) {
      PyErr_SetString(PyExc_ValueError,
                      "rows must be a list or contiguous 1-D object array");
      return nullptr;
    }
    n = PyArray_DIM(a, 0);
    items = (PyObject **)PyArray_BYTES(a);
  } else {
    PyErr_SetString(PyExc_TypeError, "rows must be a list or object ndarray");
    return nullptr;
  }
  const uint8_t *salt = (const uint8_t *)PyBytes_AS_STRING(salt_obj);
  size_t saltlen = (size_t)PyBytes_GET_SIZE(salt_obj);
  npy_intp dims[2] = {n, 16};
  PyObject *out = PyArray_SimpleNew(2, dims, NPY_UINT8);
  if (!out) return nullptr;
  uint8_t *ob = (uint8_t *)PyArray_BYTES((PyArrayObject *)out);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *row = is_list ? PyList_GET_ITEM(rows, i) : items[i];
    B2BCtx c;
    b2b_init(&c);
    if (saltlen) b2b_update(&c, salt, saltlen);
    int rc = 0;
    if (bare) {
      rc = feed_value(&c, row, pointer_type, error_obj, 0);
    } else if (PyTuple_CheckExact(row) || PyList_CheckExact(row)) {
      int is_tuple = PyTuple_CheckExact(row);
      Py_ssize_t sz =
          is_tuple ? PyTuple_GET_SIZE(row) : PyList_GET_SIZE(row);
      for (Py_ssize_t j = 0; j < sz; j++) {
        PyObject *v =
            is_tuple ? PyTuple_GET_ITEM(row, j) : PyList_GET_ITEM(row, j);
        rc = feed_value(&c, v, pointer_type, error_obj, 0);
        if (rc != 0) break;
      }
    } else {
      rc = 1; /* exotic row container: fallback iterates it */
    }
    if (rc < 0) {
      Py_DECREF(out);
      return nullptr;
    }
    if (rc == 1) {
      if (fallback_digest(fallback, row, ob + i * 16) < 0) {
        Py_DECREF(out);
        return nullptr;
      }
    } else {
      b2b_final(&c, ob + i * 16);
    }
  }
  return out;
}

/* 16 little-endian bytes (u128) mod n — identical to Python int % n for
 * the non-negative 128-bit keys/digests this is applied to. */
inline int64_t mod_u128(const uint8_t b[16], uint64_t n) {
  unsigned __int128 x;
  memcpy(&x, b, 16); /* little-endian host assumed, as in b2b_compress */
  return (int64_t)(x % n);
}

/* shard_values(values, salt, n, Pointer, ERROR, fallback)
 *   -> int64[n] worker ids | None (whole-call bail).
 * The batched routing._shard_of: exact Pointers take int(v) % n on their
 * key bytes; everything else digests (salt + value) and folds mod n;
 * values the native serializer cannot feed go through fallback(v) ->
 * bytes16 (which carries the TypeError->repr rule). Pointer SUBCLASSES
 * bail the whole call — isinstance semantics route them to int(v) % n,
 * which only the Python path does safely for arbitrary ints. */
PyObject *shard_values(PyObject *, PyObject *args) {
  HIT(H_SHARD_VALUES);
  PyObject *values, *salt_obj, *pointer_type, *error_obj, *fallback;
  Py_ssize_t nshards;
  if (!PyArg_ParseTuple(args, "O!O!nOOO", &PyList_Type, &values,
                        &PyBytes_Type, &salt_obj, &nshards, &pointer_type,
                        &error_obj, &fallback))
    return nullptr;
  if (nshards <= 0 || !PyType_Check(pointer_type)) Py_RETURN_NONE;
  uint64_t nn = (uint64_t)nshards;
  const uint8_t *salt = (const uint8_t *)PyBytes_AS_STRING(salt_obj);
  size_t saltlen = (size_t)PyBytes_GET_SIZE(salt_obj);
  Py_ssize_t n = PyList_GET_SIZE(values);
  npy_intp dims[1] = {n};
  PyObject *out = PyArray_SimpleNew(1, dims, NPY_INT64);
  if (!out) return nullptr;
  npy_int64 *od = (npy_int64 *)PyArray_BYTES((PyArrayObject *)out);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *v = PyList_GET_ITEM(values, i);
    uint8_t digest[16];
    if ((PyObject *)Py_TYPE(v) == pointer_type) {
      if (key_bytes(v, digest) < 0) {
        Py_DECREF(out);
        return nullptr;
      }
      od[i] = mod_u128(digest, nn);
      continue;
    }
    if (PyObject_TypeCheck(v, (PyTypeObject *)pointer_type)) {
      Py_DECREF(out);
      Py_RETURN_NONE;
    }
    B2BCtx c;
    b2b_init(&c);
    if (saltlen) b2b_update(&c, salt, saltlen);
    int rc = feed_value(&c, v, pointer_type, error_obj, 0);
    if (rc < 0) {
      Py_DECREF(out);
      return nullptr;
    }
    if (rc == 1) {
      if (fallback_digest(fallback, v, digest) < 0) {
        Py_DECREF(out);
        return nullptr;
      }
    } else {
      b2b_final(&c, digest);
    }
    od[i] = mod_u128(digest, nn);
  }
  return out;
}

/* entries_to_side(entries, on_cols, arity, Pointer)
 *   -> (kb, [col ndarrays]) | None (bail to the Python path).
 * One pass builds what JoinNode._side_from_batch assembles from row
 * entries: the (n,16) key-byte matrix plus every column as a typed array
 * (int64/float64/bool for clean exact-typed columns, object otherwise).
 * Bails whenever the Python screens would: any diff != 1, a non-exact
 * Pointer key, or a join-key column that is not cleanly numeric/bool
 * (string join keys keep their Python-path handling). */
PyObject *entries_to_side(PyObject *, PyObject *args) {
  HIT(H_ENTRIES_TO_SIDE);
  PyObject *entries, *on_cols, *pointer_type;
  Py_ssize_t arity;
  if (!PyArg_ParseTuple(args, "O!O!nO", &PyList_Type, &entries,
                        &PyList_Type, &on_cols, &arity, &pointer_type))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(entries);
  Py_ssize_t k = PyList_GET_SIZE(on_cols);
  if (n == 0 || arity <= 0) Py_RETURN_NONE;
  std::vector<char> is_jk((size_t)arity, 0);
  for (Py_ssize_t c = 0; c < k; c++) {
    Py_ssize_t idx = PyLong_AsSsize_t(PyList_GET_ITEM(on_cols, c));
    if (idx == -1 && PyErr_Occurred()) return nullptr;
    if (idx < 0 || idx >= arity) Py_RETURN_NONE;
    is_jk[(size_t)idx] = 1;
  }
  npy_intp kdims[2] = {n, 16};
  PyObject *kb = PyArray_SimpleNew(2, kdims, NPY_UINT8);
  if (!kb) return nullptr;
  uint8_t *kdata = (uint8_t *)PyArray_BYTES((PyArrayObject *)kb);
  std::vector<ColKind> kinds((size_t)arity, K_UNSET);
  /* pass 1: screens (shape, diffs, exact-Pointer keys) + column kinds */
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(entries, i);
    if (!PyTuple_Check(e) || PyTuple_GET_SIZE(e) != 3) goto bail;
    {
      PyObject *key = PyTuple_GET_ITEM(e, 0);
      PyObject *row = PyTuple_GET_ITEM(e, 1);
      PyObject *diff = PyTuple_GET_ITEM(e, 2);
      if (!PyLong_Check(diff) || PyLong_AsLong(diff) != 1) {
        if (PyErr_Occurred()) goto fail;
        goto bail;
      }
      if ((PyObject *)Py_TYPE(key) != pointer_type) goto bail;
      if (key_bytes(key, kdata + i * 16) < 0) goto fail;
      if (!PyTuple_Check(row) || PyTuple_GET_SIZE(row) != arity) goto bail;
      for (Py_ssize_t c = 0; c < arity; c++) {
        if (kinds[(size_t)c] == K_FAIL) continue;
        PyTypeObject *t = Py_TYPE(PyTuple_GET_ITEM(row, c));
        ColKind kc = t == &PyLong_Type    ? K_INT
                     : t == &PyFloat_Type ? K_FLOAT
                     : t == &PyBool_Type  ? K_BOOL
                                          : K_FAIL;
        if (kinds[(size_t)c] == K_UNSET)
          kinds[(size_t)c] = kc;
        else if (kinds[(size_t)c] != kc)
          kinds[(size_t)c] = K_FAIL;
      }
    }
  }
  for (Py_ssize_t c = 0; c < arity; c++)
    if (is_jk[(size_t)c] && kinds[(size_t)c] == K_FAIL)
      goto bail; /* string/object join keys: Python path semantics */
  /* pass 2: typed column fill */
  {
    PyObject *cols = PyList_New(arity);
    if (!cols) goto fail;
    for (Py_ssize_t c = 0; c < arity; c++) {
      ColKind kind = kinds[(size_t)c];
      npy_intp dims[1] = {n};
      PyObject *arr = nullptr;
      if (kind == K_INT) {
        arr = PyArray_SimpleNew(1, dims, NPY_INT64);
        if (!arr) goto fail_cols;
        npy_int64 *d = (npy_int64 *)PyArray_BYTES((PyArrayObject *)arr);
        for (Py_ssize_t i = 0; i < n; i++) {
          PyObject *v = PyTuple_GET_ITEM(
              PyTuple_GET_ITEM(PyList_GET_ITEM(entries, i), 1), c);
          int overflow = 0;
          long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
          if (overflow || (x == -1 && PyErr_Occurred())) {
            PyErr_Clear();
            Py_DECREF(arr);
            arr = nullptr;
            if (is_jk[(size_t)c]) {
              Py_DECREF(cols);
              goto bail; /* bigint join key: Python bails this side too */
            }
            kind = K_FAIL; /* bigint payload column: keep exact objects */
            break;
          }
          d[i] = (npy_int64)x;
        }
      } else if (kind == K_FLOAT) {
        arr = PyArray_SimpleNew(1, dims, NPY_FLOAT64);
        if (!arr) goto fail_cols;
        npy_double *d = (npy_double *)PyArray_BYTES((PyArrayObject *)arr);
        for (Py_ssize_t i = 0; i < n; i++)
          d[i] = PyFloat_AS_DOUBLE(PyTuple_GET_ITEM(
              PyTuple_GET_ITEM(PyList_GET_ITEM(entries, i), 1), c));
      } else if (kind == K_BOOL) {
        arr = PyArray_SimpleNew(1, dims, NPY_BOOL);
        if (!arr) goto fail_cols;
        npy_bool *d = (npy_bool *)PyArray_BYTES((PyArrayObject *)arr);
        for (Py_ssize_t i = 0; i < n; i++)
          d[i] = PyTuple_GET_ITEM(
                     PyTuple_GET_ITEM(PyList_GET_ITEM(entries, i), 1), c) ==
                 Py_True;
      }
      if (kind == K_FAIL || kind == K_UNSET) {
        arr = PyArray_SimpleNew(1, dims, NPY_OBJECT);
        if (!arr) goto fail_cols;
        PyObject **d = (PyObject **)PyArray_BYTES((PyArrayObject *)arr);
        memset(d, 0, sizeof(PyObject *) * (size_t)n);
        for (Py_ssize_t i = 0; i < n; i++) {
          PyObject *v = PyTuple_GET_ITEM(
              PyTuple_GET_ITEM(PyList_GET_ITEM(entries, i), 1), c);
          Py_INCREF(v);
          d[i] = v;
        }
      }
      PyList_SET_ITEM(cols, c, arr);
    }
    return Py_BuildValue("(NN)", kb, cols);
  fail_cols:
    Py_DECREF(cols);
    goto fail;
  }
bail:
  Py_DECREF(kb);
  Py_RETURN_NONE;
fail:
  Py_DECREF(kb);
  return nullptr;
}

inline uint64_t mix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

/* match_pairs_i64(l_cols, r_cols) -> (l_idx, r_idx).
 * Hash-join core over dtype-unified int64 code columns, result-identical
 * to _match_join_pairs_multi INCLUDING output order: the larger side
 * probes in row order (ties probe left), and each probe row's matches
 * list the build side ascending. Runs GIL-free over raw buffers. */
PyObject *match_pairs_i64(PyObject *, PyObject *args) {
  HIT(H_MATCH_PAIRS_I64);
  PyObject *l_cols, *r_cols;
  if (!PyArg_ParseTuple(args, "O!O!", &PyList_Type, &l_cols, &PyList_Type,
                        &r_cols))
    return nullptr;
  Py_ssize_t k = PyList_GET_SIZE(l_cols);
  if (k < 1 || PyList_GET_SIZE(r_cols) != k) {
    PyErr_SetString(PyExc_ValueError, "need matching non-empty column lists");
    return nullptr;
  }
  std::vector<const int64_t *> lp((size_t)k), rp((size_t)k);
  Py_ssize_t nl = -1, nr = -1;
  for (int side = 0; side < 2; side++) {
    PyObject *cols = side == 0 ? l_cols : r_cols;
    for (Py_ssize_t c = 0; c < k; c++) {
      PyObject *col = PyList_GET_ITEM(cols, c);
      if (!PyArray_Check(col)) {
        PyErr_SetString(PyExc_TypeError, "columns must be ndarrays");
        return nullptr;
      }
      PyArrayObject *a = (PyArrayObject *)col;
      if (PyArray_NDIM(a) != 1 || PyArray_TYPE(a) != NPY_INT64 ||
          !PyArray_IS_C_CONTIGUOUS(a)) {
        PyErr_SetString(PyExc_ValueError,
                        "columns must be contiguous 1-D int64");
        return nullptr;
      }
      Py_ssize_t len = PyArray_DIM(a, 0);
      Py_ssize_t &expect = side == 0 ? nl : nr;
      if (expect < 0)
        expect = len;
      else if (expect != len) {
        PyErr_SetString(PyExc_ValueError, "column length mismatch");
        return nullptr;
      }
      (side == 0 ? lp : rp)[(size_t)c] =
          (const int64_t *)PyArray_BYTES(a);
    }
  }
  /* probe = larger side; ties probe left (matches _match_join_pairs) */
  int probe_is_left = nl >= nr;
  const std::vector<const int64_t *> &pc = probe_is_left ? lp : rp;
  const std::vector<const int64_t *> &bc = probe_is_left ? rp : lp;
  Py_ssize_t np_ = probe_is_left ? nl : nr;
  Py_ssize_t nb = probe_is_left ? nr : nl;
  std::vector<int64_t> out_p, out_b;
  if (np_ > 0 && nb > 0) {
    Py_BEGIN_ALLOW_THREADS;
    size_t cap = 8;
    while ((Py_ssize_t)cap < 2 * nb) cap <<= 1;
    std::vector<int64_t> head(cap, -1), nxt((size_t)nb);
    /* reverse-order prepends leave each bucket chain ascending by index */
    for (Py_ssize_t r = nb - 1; r >= 0; r--) {
      uint64_t h = 0;
      for (Py_ssize_t c = 0; c < k; c++)
        h = mix64(h ^ (uint64_t)bc[(size_t)c][r]);
      size_t b = (size_t)h & (cap - 1);
      nxt[(size_t)r] = head[b];
      head[b] = r;
    }
    for (Py_ssize_t i = 0; i < np_; i++) {
      uint64_t h = 0;
      for (Py_ssize_t c = 0; c < k; c++)
        h = mix64(h ^ (uint64_t)pc[(size_t)c][i]);
      for (int64_t j = head[(size_t)h & (cap - 1)]; j != -1;
           j = nxt[(size_t)j]) {
        int eq = 1;
        for (Py_ssize_t c = 0; c < k; c++)
          if (pc[(size_t)c][i] != bc[(size_t)c][j]) {
            eq = 0;
            break;
          }
        if (eq) {
          out_p.push_back(i);
          out_b.push_back(j);
        }
      }
    }
    Py_END_ALLOW_THREADS;
  }
  npy_intp dims[1] = {(npy_intp)out_p.size()};
  PyObject *l_idx = PyArray_SimpleNew(1, dims, NPY_INT64);
  PyObject *r_idx = PyArray_SimpleNew(1, dims, NPY_INT64);
  if (!l_idx || !r_idx) {
    Py_XDECREF(l_idx);
    Py_XDECREF(r_idx);
    return nullptr;
  }
  if (!out_p.empty()) {
    memcpy(PyArray_BYTES((PyArrayObject *)(probe_is_left ? l_idx : r_idx)),
           out_p.data(), out_p.size() * 8);
    memcpy(PyArray_BYTES((PyArrayObject *)(probe_is_left ? r_idx : l_idx)),
           out_b.data(), out_b.size() * 8);
  }
  return Py_BuildValue("(NN)", l_idx, r_idx);
}

/* session_overlay(buffer, state, upsert) -> entries list | None.
 * The InputSession.flush overlay loops: resolve each buffered update
 * against prior state plus this commit's earlier updates. `state` is
 * only read; the overlay dict lives and dies here. Bails (None) on any
 * malformed buffer entry; comparison errors (e.g. ndarray cells in a
 * remove) propagate exactly as the Python loop would raise them. */
PyObject *session_overlay(PyObject *, PyObject *args) {
  HIT(H_SESSION_OVERLAY);
  PyObject *buffer, *state;
  int upsert;
  if (!PyArg_ParseTuple(args, "O!O!p", &PyList_Type, &buffer, &PyDict_Type,
                        &state, &upsert))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(buffer);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(buffer, i);
    if (!PyTuple_Check(e) || PyTuple_GET_SIZE(e) != 3 ||
        !PyLong_Check(PyTuple_GET_ITEM(e, 2)))
      Py_RETURN_NONE;
    if (upsert && PyTuple_GET_ITEM(e, 1) == Py_None) {
      long long d = PyLong_AsLongLong(PyTuple_GET_ITEM(e, 2));
      if (d == -1 && PyErr_Occurred()) return nullptr;
      if (d > 0) Py_RETURN_NONE; /* Python path asserts on this shape */
    }
  }
  PyObject *overlay = PyDict_New();
  PyObject *out = PyList_New(0);
  if (!overlay || !out) {
    Py_XDECREF(overlay);
    Py_XDECREF(out);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *e = PyList_GET_ITEM(buffer, i);
    PyObject *key = PyTuple_GET_ITEM(e, 0);
    PyObject *row = PyTuple_GET_ITEM(e, 1);
    PyObject *diff = PyTuple_GET_ITEM(e, 2);
    long long d = PyLong_AsLongLong(diff);
    if (d == -1 && PyErr_Occurred()) goto fail;
    /* effective(key): overlay wins (None = removed), else prior state */
    PyObject *prev = PyDict_GetItemWithError(overlay, key);
    if (!prev) {
      if (PyErr_Occurred()) goto fail;
      prev = PyDict_GetItemWithError(state, key);
      if (!prev && PyErr_Occurred()) goto fail;
    }
    if (prev == Py_None) prev = nullptr;
    if (upsert) {
      if (d > 0) {
        if (prev) {
          PyObject *retract = Py_BuildValue("(OOi)", key, prev, -1);
          if (!retract || PyList_Append(out, retract) < 0) {
            Py_XDECREF(retract);
            goto fail;
          }
          Py_DECREF(retract);
        }
        PyObject *ins = Py_BuildValue("(OOi)", key, row, 1);
        if (!ins || PyList_Append(out, ins) < 0) {
          Py_XDECREF(ins);
          goto fail;
        }
        Py_DECREF(ins);
        if (PyDict_SetItem(overlay, key, row) < 0) goto fail;
      } else if (prev) {
        PyObject *retract = Py_BuildValue("(OOi)", key, prev, -1);
        if (!retract || PyList_Append(out, retract) < 0) {
          Py_XDECREF(retract);
          goto fail;
        }
        Py_DECREF(retract);
        if (PyDict_SetItem(overlay, key, Py_None) < 0) goto fail;
      }
    } else {
      if (d < 0 && row == Py_None) {
        if (!prev) continue; /* row-less removal of an absent key */
        row = prev;
      }
      /* appending before the overlay update keeps `row` (possibly
       * borrowed from the overlay) alive across the SetItem below; the
       * Python loop's append-after order is observably identical */
      PyObject *entry = PyTuple_New(3);
      if (!entry) goto fail;
      Py_INCREF(key);
      PyTuple_SET_ITEM(entry, 0, key);
      Py_INCREF(row);
      PyTuple_SET_ITEM(entry, 1, row);
      Py_INCREF(diff);
      PyTuple_SET_ITEM(entry, 2, diff);
      if (PyList_Append(out, entry) < 0) {
        Py_DECREF(entry);
        goto fail;
      }
      Py_DECREF(entry);
      if (d > 0) {
        if (PyDict_SetItem(overlay, key, row) < 0) goto fail;
      } else {
        PyObject *eff = prev ? prev : Py_None;
        int eq = PyObject_RichCompareBool(eff, row, Py_EQ);
        if (eq < 0) goto fail; /* e.g. ndarray cells: Python raises too */
        if (eq && PyDict_SetItem(overlay, key, Py_None) < 0) goto fail;
      }
    }
  }
  Py_DECREF(overlay);
  return out;
fail:
  Py_DECREF(overlay);
  Py_DECREF(out);
  return nullptr;
}

PyMethodDef methods[] = {
    {"pointers_to_bytes", pointers_to_bytes, METH_VARARGS,
     "pointers_to_bytes(keys) -> (n,16) uint8 | None"},
    {"bytes_to_pointers", bytes_to_pointers, METH_VARARGS,
     "bytes_to_pointers(arr, Pointer) -> list[Pointer]"},
    {"hash_join_pairs", hash_join_pairs, METH_VARARGS,
     "hash_join_pairs(lbytes, rbytes) -> (n,16) uint8"},
    {"entry_keys_bytes", entry_keys_bytes, METH_VARARGS,
     "entry_keys_bytes(entries, Pointer) -> (n,16) uint8 | None"},
    {"columns_to_entries", columns_to_entries, METH_VARARGS,
     "columns_to_entries(keys, cols, diffs|None) -> entries"},
    {"join_insert_inner", join_insert_inner, METH_VARARGS,
     "join_insert_inner(l_entries, r_entries, l_on, r_on, l_arr, r_arr, "
     "ERROR, Pointer) -> entries|None"},
    {"consolidate", consolidate, METH_VARARGS,
     "consolidate(entries) -> (entries|None, insert_only)"},
    {"apply_state", apply_state, METH_VARARGS,
     "apply_state(state, entries, insert_only)"},
    {"build_entries", build_entries, METH_VARARGS,
     "build_entries(entries, columns) -> entries"},
    {"filter_truthy", filter_truthy, METH_VARARGS,
     "filter_truthy(entries, col) -> entries|None"},
    {"extract_column", extract_column, METH_VARARGS,
     "extract_column(seq, col, from_entries) -> ndarray|None"},
    {"entry_diffs", entry_diffs, METH_VARARGS,
     "entry_diffs(entries) -> int64 ndarray"},
    {"hash_tuples_batch", hash_tuples_batch, METH_VARARGS,
     "hash_tuples_batch(rows, salt, bare, Pointer, ERROR, fallback) -> "
     "(n,16) uint8"},
    {"shard_values", shard_values, METH_VARARGS,
     "shard_values(values, salt, n, Pointer, ERROR, fallback) -> "
     "int64[n] | None"},
    {"entries_to_side", entries_to_side, METH_VARARGS,
     "entries_to_side(entries, on_cols, arity, Pointer) -> "
     "(kb, cols) | None"},
    {"match_pairs_i64", match_pairs_i64, METH_VARARGS,
     "match_pairs_i64(l_cols, r_cols) -> (l_idx, r_idx)"},
    {"session_overlay", session_overlay, METH_VARARGS,
     "session_overlay(buffer, state, upsert) -> entries | None"},
    {"hit_counts", hit_counts, METH_NOARGS,
     "hit_counts() -> {kernel: calls}"},
    {"kernel_ns", kernel_ns, METH_NOARGS,
     "kernel_ns() -> {kernel: cumulative nanoseconds}"},
    {"reset_hit_counts", reset_hit_counts, METH_NOARGS,
     "reset_hit_counts()"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT,
                         "_enginecore",
                         "Native engine-core kernels",
                         -1,
                         methods,
                         nullptr,
                         nullptr,
                         nullptr,
                         nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__enginecore(void) {
  import_array();
  return PyModule_Create(&moduledef);
}
